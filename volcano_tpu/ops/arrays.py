"""Snapshot flattening: ClusterInfo -> padded device arrays.

This is the TPU equivalent of the reference's parallel snapshot clone
(cache.go:693-742): each session the host flattens the cluster into
fixed-shape float32/int32 arrays (padded to compile buckets so XLA reuses
compiled executables across cycles) and ships them to the device in one
transfer. Mapping tables (tasks_list / nodes_list / jobs_list) translate
solver outputs back into TaskInfo/NodeInfo objects for Statement replay.

Predicate masks are precomputed host-side per unique constraint signature
(node selector + affinity + tolerations hash) so the device matrix is a
cheap gather: sig_masks[S, N] with S = number of distinct signatures, which
is tiny in practice even when T is 10k.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import (
    JobInfo, NodeInfo, Resource, ResourceVocab, TaskInfo, TaskStatus,
    MIN_MEMORY, MIN_MILLI_CPU, MIN_MILLI_SCALAR,
)

#: compile-bucket sizes: quarter-steps between powers of two, floor 8 —
#: keeps the number of distinct compiled shapes logarithmic in cluster size
#: while capping padding overhead at 25%
def bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        for frac in (1.25, 1.5, 1.75, 2.0):
            cand = int(b * frac)
            if cand >= n:
                return cand
        b *= 2
    return b


def _match_node_selector(selector: Dict[str, str], node) -> bool:
    labels = node.labels or {}
    return all(labels.get(k) == v for k, v in selector.items())


def taint_tolerated(taint: dict, tolerations: List[dict]) -> bool:
    for tol in tolerations or []:
        op = tol.get("operator", "Equal")
        if tol.get("key") and tol["key"] != taint.get("key"):
            continue
        if op == "Equal" and tol.get("value") != taint.get("value"):
            continue
        if tol.get("effect") and tol["effect"] != taint.get("effect"):
            continue
        return True
    return False


def _tolerates(tolerations: List[dict], node) -> bool:
    """NoSchedule/NoExecute taints must be tolerated (predicates plugin)."""
    for taint in node.taints or []:
        if taint.get("effect") not in ("NoSchedule", "NoExecute"):
            continue
        if not taint_tolerated(taint, tolerations):
            return False
    return True


def _node_affinity_match(affinity: Optional[dict], node) -> bool:
    """requiredDuringSchedulingIgnoredDuringExecution node affinity subset:
    matchExpressions with In/NotIn/Exists/DoesNotExist operators."""
    if not affinity:
        return True
    na = affinity.get("nodeAffinity") or {}
    req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not req:
        return True
    labels = node.labels or {}
    for term in req.get("nodeSelectorTerms", []):
        ok = True
        for expr in term.get("matchExpressions", []):
            key, op = expr.get("key"), expr.get("operator")
            vals = expr.get("values", [])
            has = key in labels
            if op == "In":
                ok &= has and labels[key] in vals
            elif op == "NotIn":
                ok &= not (has and labels[key] in vals)
            elif op == "Exists":
                ok &= has
            elif op == "DoesNotExist":
                ok &= not has
            if not ok:
                break
        if ok:
            return True  # terms are ORed
    return False


def _signature(task: TaskInfo) -> str:
    s = task.sig_cache
    if s is not None:
        return s
    pod = task.pod
    if not pod.node_selector and pod.affinity is None and not pod.tolerations:
        ports = pod.ports()
        if not ports:
            s = ""  # unconstrained fast path (the common case)
        else:
            s = json.dumps({"ports": sorted(ports)})
    else:
        s = json.dumps({
            "sel": sorted((pod.node_selector or {}).items()),
            "aff": pod.affinity,
            "tol": pod.tolerations,
            "ports": sorted(pod.ports()),
        }, sort_keys=True, default=str)
    task.sig_cache = s
    return s


@dataclass
class ScoreParams:
    """Scalar weights feeding the on-device scoring families. Plugins set
    these during OnSessionOpen (binpack/nodeorder register here instead of
    per-(task,node) Python callbacks)."""

    binpack_weight: float = 0.0
    binpack_res_weights: Optional[np.ndarray] = None  # [R]
    least_req_weight: float = 0.0
    most_req_weight: float = 0.0
    balanced_weight: float = 0.0
    # static per-node score added for every task (e.g. node-affinity
    # preferences evaluated host-side): [N]
    node_static: Optional[np.ndarray] = None

    def resolved(self, R: int, N: int) -> "ScoreParams":
        p = ScoreParams(
            binpack_weight=self.binpack_weight,
            least_req_weight=self.least_req_weight,
            most_req_weight=self.most_req_weight,
            balanced_weight=self.balanced_weight)
        p.binpack_res_weights = (
            np.ones(R, dtype=np.float32) if self.binpack_res_weights is None
            else np.asarray(self.binpack_res_weights, dtype=np.float32))
        p.node_static = (
            np.zeros(N, dtype=np.float32) if self.node_static is None
            else np.asarray(self.node_static, dtype=np.float32))
        return p


@dataclass
class SnapshotArrays:
    """Padded array view of one session's decision problem."""

    vocab: ResourceVocab
    # -- tasks (pending tasks of schedulable jobs, in scheduling order) -----
    tasks_list: List[TaskInfo] = field(default_factory=list)
    task_init_req: np.ndarray = None    # [T,R] launch request (fit check)
    task_req: np.ndarray = None         # [T,R] running request (accounting)
    task_job: np.ndarray = None         # [T] -> job index
    task_rank: np.ndarray = None        # [T] global priority order (0 first)
    task_sig: np.ndarray = None         # [T] -> signature index
    task_counts_ready: np.ndarray = None  # [T] bool: counts toward gang
    task_valid: np.ndarray = None       # [T] bool
    # -- jobs ----------------------------------------------------------------
    jobs_list: List[JobInfo] = field(default_factory=list)
    job_min: np.ndarray = None          # [J]
    job_ready_base: np.ndarray = None   # [J] ready_task_num at snapshot
    job_queue: np.ndarray = None        # [J] -> queue index
    job_valid: np.ndarray = None        # [J] bool
    # DRF ordering inputs (filled by the allocate action from the drf
    # plugin's session-open attrs; zeros when drf is inactive)
    job_drf_allocated: np.ndarray = None  # [J,R]
    drf_total: np.ndarray = None          # [R]
    #: static MAJOR ordering key for the in-kernel drf/hdrf re-rank: dense
    #: rank from the job-order providers that precede drf in the tiers
    #: (priority/gang) — live shares only break its ties, so a strict
    #: priority is never inverted by a share re-rank
    job_drf_prerank: np.ndarray = None    # [J] int32
    # hierarchical-DRF tree (ops.hdrf.build_hdrf; None unless hdrf active)
    hdrf_parent: np.ndarray = None        # [H]
    hdrf_weight: np.ndarray = None        # [H]
    hdrf_depth: np.ndarray = None         # [H]
    hdrf_is_leaf: np.ndarray = None       # [H] bool
    hdrf_leaf_req: np.ndarray = None      # [H,R]
    hdrf_job_leaf: np.ndarray = None      # [J]
    hdrf_ancestors: np.ndarray = None     # [J,D]
    hdrf_total_allocated: np.ndarray = None  # [R]
    # -- nodes ---------------------------------------------------------------
    nodes_list: List[NodeInfo] = field(default_factory=list)
    node_idle: np.ndarray = None        # [N,R]
    node_extra_future: np.ndarray = None  # [N,R] releasing - pipelined
    node_used: np.ndarray = None        # [N,R]
    node_alloc: np.ndarray = None       # [N,R] allocatable
    node_npods: np.ndarray = None       # [N]
    node_max_pods: np.ndarray = None    # [N]
    node_valid: np.ndarray = None       # [N] bool
    # -- predicate signatures ------------------------------------------------
    sig_masks: np.ndarray = None        # [S,N] bool
    # -- queues --------------------------------------------------------------
    queues_list: List[str] = field(default_factory=list)
    queue_weight: np.ndarray = None     # [Q] (0 = padded/absent queue)
    queue_capability: np.ndarray = None  # [Q,R] (inf where uncapped)
    queue_allocated: np.ndarray = None  # [Q,R]
    queue_request: np.ndarray = None    # [Q,R] allocated + pending requests
    # -- misc ----------------------------------------------------------------
    thresholds: np.ndarray = None       # [R]
    scalar_dim_mask: np.ndarray = None  # [R] bool: dims 2+ (ignorable)

    @property
    def T(self) -> int:
        return self.task_init_req.shape[0]

    @property
    def N(self) -> int:
        return self.node_idle.shape[0]

    @property
    def R(self) -> int:
        return self.task_init_req.shape[1]

    @property
    def J(self) -> int:
        return self.job_min.shape[0]

    def packed(self):
        """Pack the solver arrays into one f32 buffer + one i32 buffer so the
        per-session host->device transfer is two puts instead of ~20 (the
        per-transfer latency through the device tunnel dominates at small
        sizes). Returns (fbuf, ibuf, layout); feed to solve_allocate_packed.
        """
        d = self.device_dict()
        fparts, iparts, layout = [], [], []
        foff = ioff = 0
        for k in sorted(d):
            v = d[k]
            if v.dtype == np.float32:
                fparts.append(v.ravel())
                layout.append((k, "f", foff, v.size, v.shape))
                foff += v.size
            elif v.dtype == np.bool_:
                iparts.append(v.ravel().astype(np.int32))
                layout.append((k, "b", ioff, v.size, v.shape))
                ioff += v.size
            else:
                iparts.append(v.ravel().astype(np.int32))
                layout.append((k, "i", ioff, v.size, v.shape))
                ioff += v.size
        fbuf = np.concatenate(fparts) if fparts else np.zeros(0, np.float32)
        ibuf = np.concatenate(iparts) if iparts else np.zeros(0, np.int32)
        return fbuf, ibuf, tuple(layout)

    def fill_queue_demand(self) -> None:
        """Fill queue_request from the flattened jobs' total requests — a
        stand-in for the proportion plugin's session-open attrs when no
        session is in the loop (benches, dryruns, kernel-level tests).
        The allocate action overwrites these from the plugin instead."""
        self.queue_request[:] = 0.0
        for j, job in enumerate(self.jobs_list):
            self.queue_request[self.job_queue[j]] += \
                job.total_request.to_vector(self.vocab)

    def device_dict(self) -> Dict[str, np.ndarray]:
        """The arrays the solver kernel consumes (one host->device hop).
        hdrf arrays ride along only when the hierarchy was built (their
        presence changes the packed layout, i.e. compiles an hdrf
        variant)."""
        d = self._base_device_dict()
        if self.hdrf_parent is not None:
            d.update({
                "hdrf_parent": self.hdrf_parent,
                "hdrf_weight": self.hdrf_weight,
                "hdrf_depth": self.hdrf_depth,
                "hdrf_is_leaf": self.hdrf_is_leaf,
                "hdrf_leaf_req": self.hdrf_leaf_req,
                "hdrf_job_leaf": self.hdrf_job_leaf,
                "hdrf_ancestors": self.hdrf_ancestors,
                "hdrf_total_allocated": self.hdrf_total_allocated,
            })
        return d

    def _base_device_dict(self) -> Dict[str, np.ndarray]:
        return {
            "task_init_req": self.task_init_req,
            "task_req": self.task_req,
            "task_job": self.task_job,
            "task_rank": self.task_rank,
            "task_sig": self.task_sig,
            "task_counts_ready": self.task_counts_ready,
            "task_valid": self.task_valid,
            "job_min": self.job_min,
            "job_ready_base": self.job_ready_base,
            "job_queue": self.job_queue,
            "job_valid": self.job_valid,
            "job_drf_allocated": self.job_drf_allocated,
            "drf_total": self.drf_total,
            "job_drf_prerank": self.job_drf_prerank,
            "node_idle": self.node_idle,
            "node_extra_future": self.node_extra_future,
            "node_used": self.node_used,
            "node_alloc": self.node_alloc,
            "node_npods": self.node_npods,
            "node_max_pods": self.node_max_pods,
            "node_valid": self.node_valid,
            "sig_masks": self.sig_masks,
            "queue_weight": self.queue_weight,
            "queue_capability": self.queue_capability,
            "queue_allocated": self.queue_allocated,
            "queue_request": self.queue_request,
            "thresholds": self.thresholds,
            "scalar_dim_mask": self.scalar_dim_mask,
        }


class FlattenCache:
    """Incremental cross-session flatten state.

    The reference deep-clones the whole cluster every cycle (cache.go:693-742,
    one goroutine per job); the TPU build instead keeps the device-ready
    columns warm across sessions and recomputes only what changed, keyed on
    ``JobInfo.flat_version`` / ``NodeInfo.flat_version`` bumps. A cold cache
    (or ``cache=None``) reproduces the full flatten; results are identical
    either way because every entry is verified against the live objects'
    versions and task-uid sequences before reuse.
    """

    def __init__(self, vocab: Optional[ResourceVocab] = None):
        self.vocab = vocab
        self.job_blocks: Dict[str, dict] = {}
        self.node_rows: Dict[str, dict] = {}
        self.sig_rows: Dict[str, tuple] = {}   # sig -> (node_key, row[N])
        self._node_key: Optional[tuple] = None
        self._node_buf: Optional[dict] = None
        self._task_key: Optional[tuple] = None
        self._task_buf: Optional[tuple] = None

    # -- per-node rows ------------------------------------------------------

    def node_row(self, ni: NodeInfo) -> dict:
        vocab = self.vocab
        R = len(vocab)
        ent = self.node_rows.get(ni.name)
        if ent is not None and ent["v"] == ni.flat_version \
                and ent["e"] == ni.flat_epoch and ent["R"] == R:
            return ent
        idle = ni.idle.to_vector(vocab)
        used = ni.used.to_vector(vocab)
        extra = ni.releasing.to_vector(vocab) - ni.pipelined.to_vector(vocab)
        alloc = ni.allocatable.to_vector(vocab)
        alloc = np.where(alloc > 0, alloc, 1.0).astype(np.float32)
        npods = sum(1 for t in ni.tasks.values()
                    if t.status != TaskStatus.PIPELINED)
        ent = {"v": ni.flat_version, "e": ni.flat_epoch, "R": R,
               "idle": idle, "used": used,
               "extra": extra, "alloc": alloc, "npods": npods,
               "maxp": ni.allocatable.max_task_num or 1 << 30}
        self.node_rows[ni.name] = ent
        return ent

    # -- per-job task blocks ------------------------------------------------

    def job_block(self, job: JobInfo, tasks: List[TaskInfo],
                  uids: List[str]) -> dict:
        vocab = self.vocab
        R = len(vocab)
        ent = self.job_blocks.get(job.uid)
        if (ent is not None and ent["v"] == job.flat_version
                and ent["R"] == R and ent["uids"] == uids):
            return ent
        k = len(tasks)
        # bulk cpu/mem extraction: one list-comprehension + np.array beats
        # 2k per-task to_vector calls ~5x (the all-cold burst flatten is
        # this loop); scalar resources overlay the rare rows after
        init = np.zeros((k, R), dtype=np.float32)
        req = np.zeros((k, R), dtype=np.float32)
        init[:, :2] = np.array(
            [(t.init_resreq.milli_cpu, t.init_resreq.memory)
             for t in tasks], dtype=np.float32).reshape(k, 2)
        req[:, :2] = np.array(
            [(t.resreq.milli_cpu, t.resreq.memory)
             for t in tasks], dtype=np.float32).reshape(k, 2)
        any_scalar = np.zeros(k, dtype=bool)
        for i, t in enumerate(tasks):
            if t.init_resreq.scalars or t.resreq.scalars:
                for name, v in t.init_resreq.scalars.items():
                    if v >= MIN_MILLI_SCALAR:
                        # vocab-independent, like Resource.is_empty
                        any_scalar[i] = True
                    idx = vocab.index(name)
                    if idx is not None:
                        init[i, idx] = v
                for name, v in t.resreq.scalars.items():
                    idx = vocab.index(name)
                    if idx is not None:
                        req[i, idx] = v
        # not is_empty(): the api.resource thresholds
        counts = ((init[:, 0] >= MIN_MILLI_CPU)
                  | (init[:, 1] >= MIN_MEMORY) | any_scalar)
        sig_uniq: List[str] = []
        sig_reps: List[TaskInfo] = []
        sig_idx: Dict[str, int] = {}
        sig_local = np.zeros(k, dtype=np.int32)
        for i, t in enumerate(tasks):
            s = _signature(t)
            li = sig_idx.get(s)
            if li is None:
                li = sig_idx[s] = len(sig_uniq)
                sig_uniq.append(s)
                sig_reps.append(t)
            sig_local[i] = li
        ent = {"v": job.flat_version, "R": R, "uids": uids,
               "init": init, "req": req, "counts": counts,
               "sig_uniq": sig_uniq, "sig_reps": sig_reps,
               "sig_local": sig_local, "min": job.min_available,
               "ready": job.ready_task_num(), "queue": job.queue}
        self.job_blocks[job.uid] = ent
        return ent

    # -- bounded size -------------------------------------------------------

    def sweep(self, live_jobs, live_nodes, live_sigs) -> None:
        """Drop entries for departed jobs/nodes/signatures once the maps grow
        well past the live set, so a churny cluster can't grow the cache
        unboundedly (job blocks pin task arrays and Pod refs)."""
        if len(self.job_blocks) > 2 * len(live_jobs) + 64:
            self.job_blocks = {k: v for k, v in self.job_blocks.items()
                               if k in live_jobs}
        if len(self.node_rows) > 2 * len(live_nodes) + 64:
            self.node_rows = {k: v for k, v in self.node_rows.items()
                              if k in live_nodes}
        if len(self.sig_rows) > 2 * len(live_sigs) + 64:
            self.sig_rows = {k: v for k, v in self.sig_rows.items()
                             if k in live_sigs}

    # -- vocab growth -------------------------------------------------------

    def ensure_names(self, resources) -> None:
        """Register any new scalar resource names (vocab only ever grows, so
        previously cached entries stay valid names-wise; width changes are
        caught by the per-entry R check)."""
        vocab = self.vocab
        for r in resources:
            for name in r.scalars:
                if vocab.index(name) is None:
                    vocab.add(name)


def flatten_snapshot(
    jobs: Dict[str, JobInfo],
    nodes: Dict[str, NodeInfo],
    tasks_in_order: List[TaskInfo],
    vocab: Optional[ResourceVocab] = None,
    queues: Optional[Dict[str, object]] = None,
    cache: Optional[FlattenCache] = None,
    grouped: Optional[List[tuple]] = None,
) -> SnapshotArrays:
    """Flatten session state into padded arrays.

    tasks_in_order: the pending tasks to place, already sorted by the
    session's namespace/queue/job/task ordering (host-side comparator pass —
    the ordering semantics stay in Python, the math goes on device).
    Tasks must be grouped by job within the order.

    Pass a persistent ``cache`` (the SchedulerCache owns one) to make the
    per-session flatten incremental: unchanged jobs reuse their cached task
    blocks, unchanged nodes their rows.

    NOTE: with a persistent cache the returned arrays alias cache-owned
    buffers that the NEXT flatten call may rewrite in place — they are valid
    for the current session only. Callers that need to retain arrays across
    sessions must copy them.
    """
    if cache is None:
        cache = FlattenCache(vocab)
    elif vocab is not None and cache.vocab is None:
        cache.vocab = vocab
    if cache.vocab is None:
        resources = []
        for ni in nodes.values():
            resources.append(ni.allocatable)
        for t in tasks_in_order:
            resources.append(t.init_resreq)
        cache.vocab = ResourceVocab.collect(resources)
    vocab = cache.vocab

    nodes_list = [n for n in nodes.values() if n.ready]
    n_tasks = len(tasks_in_order)
    n_nodes = len(nodes_list)

    # group tasks by job, preserving order (callers that already hold the
    # per-job grouping — the allocate action — pass it via `grouped` and
    # skip this O(T) pass)
    if grouped is not None:
        job_keys = [j.uid for j, _ in grouped]
        job_tasks = [ts for _, ts in grouped]
    else:
        job_keys: List[str] = []
        job_tasks: List[List[TaskInfo]] = []
        cur = None
        cur_list: List[TaskInfo] = []
        for t in tasks_in_order:
            if t.job != cur:
                cur = t.job
                cur_list = []
                job_keys.append(cur)
                job_tasks.append(cur_list)
            cur_list.append(t)
        if len(set(job_keys)) != len(job_keys):
            # non-contiguous job grouping (callers should not do this, the
            # sequential solver depends on contiguity): merge defensively
            merged: Dict[str, List[TaskInfo]] = {}
            for k, ts in zip(job_keys, job_tasks):
                merged.setdefault(k, []).extend(ts)
            job_keys = list(merged)
            job_tasks = list(merged.values())
            tasks_in_order = [t for ts in job_tasks for t in ts]
            n_tasks = len(tasks_in_order)

    # vocab growth pre-pass: only entries about to recompute can introduce
    # new names; scanning just those here is what keeps R stable below
    for j, key in enumerate(job_keys):
        ent = cache.job_blocks.get(key)
        if ent is None or ent["v"] != jobs[key].flat_version:
            cache.ensure_names(t.init_resreq for t in job_tasks[j])
            cache.ensure_names(t.resreq for t in job_tasks[j])
    for ni in nodes_list:
        ent = cache.node_rows.get(ni.name)
        if ent is None or ent["v"] != ni.flat_version:
            cache.ensure_names((ni.allocatable,))
    R = len(vocab)

    N = bucket(max(n_nodes, 1))
    T = bucket(max(n_tasks, 1))
    # +1 guarantees a padded (invalid) job slot: padded tasks point there so
    # the sequential solver's job-boundary logic never revisits a real job
    J = bucket(len(job_keys) + 1)

    arr = SnapshotArrays(vocab=vocab)
    arr.tasks_list = list(tasks_in_order)
    arr.nodes_list = nodes_list
    arr.jobs_list = [jobs[k] for k in job_keys]

    # -- task/job side, assembled from per-job cached blocks ----------------
    # wholesale fast path: if no job changed and the task sequence is
    # identical (verified via uid sequence + versions — list compares run at
    # C speed), the previous session's assembled arrays are this session's
    versions = [jobs[k].flat_version for k in job_keys]
    uid_seq = [t.uid for t in tasks_in_order]
    shape_key = (R, T, J)
    tk = cache._task_key
    if (tk is not None and tk[3] == shape_key and tk[0] == job_keys
            and tk[1] == versions and tk[2] == uid_seq):
        (arr.task_init_req, arr.task_req, arr.task_job, arr.task_rank,
         arr.task_sig, arr.task_counts_ready, arr.task_valid,
         arr.job_min, arr.job_ready_base, arr.job_queue, arr.job_valid,
         sigs, sig_tasks, queue_index, queue_names) = cache._task_buf
        return _finish(arr, cache, nodes_list, n_nodes, R, N, sigs,
                       sig_tasks, queue_index, queue_names, queues)

    # per-job cached blocks -> padded columns via one concatenate per kind
    # (numpy block copies instead of ~10 Python slice-assigns per job)
    blocks = []
    off = 0
    for j, key in enumerate(job_keys):
        k = len(job_tasks[j])
        blocks.append(cache.job_block(jobs[key], job_tasks[j],
                                      uid_seq[off:off + k]))
        off += k
    pad = T - n_tasks

    def cat2d(name):
        parts = [b[name] for b in blocks]
        if pad or not parts:
            parts = parts + [np.zeros((pad, R), dtype=np.float32)]
        return np.concatenate(parts, axis=0)

    arr.task_init_req = cat2d("init")
    arr.task_req = cat2d("req")
    counts_parts = [b["counts"] for b in blocks]
    if pad or not counts_parts:
        counts_parts = counts_parts + [np.zeros(pad, dtype=bool)]
    arr.task_counts_ready = np.concatenate(counts_parts)
    lens = np.fromiter((len(ts) for ts in job_tasks), dtype=np.int64,
                       count=len(job_tasks))
    task_job = np.full(T, J - 1, dtype=np.int32)  # padded job slot
    if n_tasks:
        task_job[:n_tasks] = np.repeat(
            np.arange(len(job_keys), dtype=np.int32), lens)
    arr.task_job = task_job
    arr.task_rank = np.arange(T, dtype=np.int32)
    arr.task_valid = np.zeros(T, dtype=bool)
    arr.task_valid[:n_tasks] = True

    sigs: Dict[str, int] = {}
    sig_tasks: List[TaskInfo] = []
    sig_parts = []
    for ent in blocks:
        remap = np.empty(max(len(ent["sig_uniq"]), 1), dtype=np.int32)
        for li, s in enumerate(ent["sig_uniq"]):
            gi = sigs.get(s)
            if gi is None:
                gi = sigs[s] = len(sig_tasks)
                sig_tasks.append(ent["sig_reps"][li])
            remap[li] = gi
        sig_parts.append(remap[ent["sig_local"]])
    if pad or not sig_parts:
        sig_parts.append(np.zeros(pad, dtype=np.int32))
    arr.task_sig = np.concatenate(sig_parts)

    arr.job_min = np.zeros(J, dtype=np.int32)
    arr.job_ready_base = np.zeros(J, dtype=np.int32)
    arr.job_queue = np.zeros(J, dtype=np.int32)
    arr.job_valid = np.zeros(J, dtype=bool)
    queue_index: Dict[str, int] = {}
    queue_names: List[str] = []
    for j, ent in enumerate(blocks):
        arr.job_min[j] = ent["min"]
        arr.job_ready_base[j] = ent["ready"]
        arr.job_valid[j] = True
        q = ent["queue"]
        qi = queue_index.get(q)
        if qi is None:
            qi = queue_index[q] = len(queue_names)
            queue_names.append(q)
        arr.job_queue[j] = qi

    cache._task_key = (job_keys, versions, uid_seq, shape_key)
    cache._task_buf = (arr.task_init_req, arr.task_req, arr.task_job,
                       arr.task_rank, arr.task_sig, arr.task_counts_ready,
                       arr.task_valid, arr.job_min, arr.job_ready_base,
                       arr.job_queue, arr.job_valid, sigs, sig_tasks,
                       queue_index, queue_names)
    return _finish(arr, cache, nodes_list, n_nodes, R, N, sigs, sig_tasks,
                   queue_index, queue_names, queues)


def _bulk_node_rows(cache, fast, buf, R: int) -> None:
    """Vectorized node-row recompute for scalar-free nodes: identical
    results (and cache entries) to FlattenCache.node_row, built as four
    [k,2] extractions instead of ~8 to_vector calls per node. The cached
    per-node entries view rows of the bulk arrays (standalone — NOT the
    session buffer, which is rewritten in place next flatten)."""
    k = len(fast)
    idle = np.zeros((k, R), np.float32)
    used = np.zeros((k, R), np.float32)
    extra = np.zeros((k, R), np.float32)
    alloc = np.zeros((k, R), np.float32)
    idle[:, :2] = np.array(
        [(ni.idle.milli_cpu, ni.idle.memory) for _, ni in fast],
        np.float32).reshape(k, 2)
    used[:, :2] = np.array(
        [(ni.used.milli_cpu, ni.used.memory) for _, ni in fast],
        np.float32).reshape(k, 2)
    # subtract in float32 like node_row's to_vector()-to_vector() (a
    # float64 intermediate here would round differently by an ulp and
    # break cold-vs-warm flatten identity)
    rel = np.array([(ni.releasing.milli_cpu, ni.releasing.memory)
                    for _, ni in fast], np.float32).reshape(k, 2)
    pip = np.array([(ni.pipelined.milli_cpu, ni.pipelined.memory)
                    for _, ni in fast], np.float32).reshape(k, 2)
    extra[:, :2] = rel - pip
    alloc[:, :2] = np.array(
        [(ni.allocatable.milli_cpu, ni.allocatable.memory)
         for _, ni in fast], np.float32).reshape(k, 2)
    alloc = np.where(alloc > 0, alloc, 1.0).astype(np.float32)
    npods = np.fromiter(
        (sum(1 for t in ni.tasks.values()
             if t.status != TaskStatus.PIPELINED) for _, ni in fast),
        np.int32, count=k)
    maxp = np.fromiter(
        (ni.allocatable.max_task_num or 1 << 30 for _, ni in fast),
        np.int64, count=k).astype(np.int32, copy=False)
    idxs = np.fromiter((i for i, _ in fast), np.int64, count=k)
    buf["idle"][idxs] = idle
    buf["extra"][idxs] = extra
    buf["used"][idxs] = used
    buf["alloc"][idxs] = alloc
    buf["npods"][idxs] = npods
    buf["maxp"][idxs] = maxp
    rows = cache.node_rows
    for j, (_, ni) in enumerate(fast):
        rows[ni.name] = {
            "v": ni.flat_version, "e": ni.flat_epoch, "R": R,
            "idle": idle[j], "used": used[j], "extra": extra[j],
            "alloc": alloc[j], "npods": int(npods[j]),
            "maxp": int(maxp[j])}


def _finish(arr, cache, nodes_list, n_nodes, R, N, sigs, sig_tasks,
            queue_index, queue_names, queues):
    vocab = arr.vocab
    # -- node side: persistent buffer, rewrite only changed rows ------------
    node_key = tuple((ni.name, ni.flat_epoch, ni.flat_version)
                     for ni in nodes_list)
    buf = cache._node_buf
    reusable = (buf is not None and buf["R"] == R and buf["N"] == N
                and len(cache._node_key) == n_nodes)
    if not reusable:
        buf = {
            "R": R, "N": N,
            "idle": np.zeros((N, R), dtype=np.float32),
            "extra": np.zeros((N, R), dtype=np.float32),
            "used": np.zeros((N, R), dtype=np.float32),
            "alloc": np.ones((N, R), dtype=np.float32),  # pads: avoid div 0
            "npods": np.zeros(N, dtype=np.int32),
            "maxp": np.zeros(N, dtype=np.int32),
            "valid": np.zeros(N, dtype=bool),
        }
        buf["valid"][:n_nodes] = True
        old_key = ()
    else:
        old_key = cache._node_key
    pending = [(i, ni) for i, ni in enumerate(nodes_list)
               if not (reusable and i < len(old_key)
                       and old_key[i] == node_key[i])]
    # cold-path vectorization (first cycle / full reship): scalar-free
    # nodes bulk-extract cpu+mem via one list comprehension per column
    # and land in the buffer as fancy-indexed scatters — the per-node
    # to_vector path costs ~11us/node, most of a 2k-node cold flatten
    if len(pending) >= 64:
        rows = cache.node_rows

        def cached_ok(ni):
            ent = rows.get(ni.name)
            return (ent is not None and ent["v"] == ni.flat_version
                    and ent["e"] == ni.flat_epoch and ent["R"] == R)

        # bulk only the nodes node_row would actually RECOMPUTE: a node
        # whose buffer row is stale but whose cache entry is still valid
        # (bucket change, node removal) is a cheap dict hit below
        fast = [(i, ni) for i, ni in pending
                if not cached_ok(ni)
                and not (ni.idle.scalars or ni.used.scalars
                         or ni.releasing.scalars or ni.pipelined.scalars
                         or ni.allocatable.scalars)]
        if len(fast) >= 64:
            _bulk_node_rows(cache, fast, buf, R)
            done = {i for i, _ in fast}
            pending = [(i, ni) for i, ni in pending if i not in done]
    for i, ni in pending:
        row = cache.node_row(ni)
        buf["idle"][i] = row["idle"]
        buf["extra"][i] = row["extra"]
        buf["used"][i] = row["used"]
        buf["alloc"][i] = row["alloc"]
        buf["npods"][i] = row["npods"]
        buf["maxp"][i] = row["maxp"]
    cache._node_key = node_key
    cache._node_buf = buf
    arr.node_idle = buf["idle"]
    arr.node_extra_future = buf["extra"]
    arr.node_used = buf["used"]
    arr.node_alloc = buf["alloc"]
    arr.node_npods = buf["npods"]
    arr.node_max_pods = buf["maxp"]
    arr.node_valid = buf["valid"]

    # -- predicate signature masks (cached per signature x node epoch) ------
    S = max(len(sigs), 1)
    arr.sig_masks = np.zeros((S, N), dtype=bool)
    if not sig_tasks:
        arr.sig_masks[:, :] = True
    # label/taint-only masks survive resource-accounting churn: they key on
    # spec versions; only port-aware masks key on the full node epoch
    spec_key = tuple((ni.name, ni.flat_epoch, ni.spec_version)
                     for ni in nodes_list)
    for s, s_idx in sigs.items():
        # (even the unconstrained "" signature must run the node loop:
        # untolerated NoSchedule taints block constraint-free pods too)
        row_key = node_key if sig_tasks[s_idx].pod.ports() else spec_key
        cached = cache.sig_rows.get(s)
        if cached is not None and cached[0] == row_key \
                and cached[1].shape[0] == N:
            arr.sig_masks[s_idx] = cached[1]
            continue
        pod = sig_tasks[s_idx].pod
        row = np.zeros(N, dtype=bool)
        for n_idx, ni in enumerate(nodes_list):
            node = ni.node
            ok = True
            if node is not None:
                ok = (_match_node_selector(pod.node_selector or {}, node)
                      and _tolerates(pod.tolerations, node)
                      and _node_affinity_match(pod.affinity, node))
                if ok and pod.ports():
                    taken = set()
                    for other in ni.tasks.values():
                        taken.update(other.pod.ports())
                    ok = not (set(pod.ports()) & taken)
            row[n_idx] = ok
        cache.sig_rows[s] = (row_key, row)
        arr.sig_masks[s_idx] = row

    # queues (water-filling inputs; overwritten by the allocate action from
    # the proportion plugin's session-open attrs when proportion is active —
    # those cover allocated/request across ALL jobs, not just pending ones)
    Q = bucket(max(len(queue_names), 1))
    arr.queues_list = queue_names
    arr.queue_weight = np.zeros(Q, dtype=np.float32)  # 0 = padded slot
    arr.queue_weight[:len(queue_names)] = 1.0
    arr.queue_capability = np.full((Q, R), np.inf, dtype=np.float32)
    arr.queue_allocated = np.zeros((Q, R), dtype=np.float32)
    arr.queue_request = np.zeros((Q, R), dtype=np.float32)
    if queues:
        for name, q_idx in queue_index.items():
            qi = queues.get(name)
            if qi is None:
                continue
            arr.queue_weight[q_idx] = getattr(qi, "weight", 1) or 1
            cap = getattr(qi, "capability", None)
            if cap:
                cap_vec = Resource.from_resource_list(cap).to_vector(vocab)
                arr.queue_capability[q_idx] = np.where(
                    cap_vec > 0, cap_vec, np.inf)

    # DRF ordering inputs default to zeros (drf inactive -> static rank);
    # the allocate action overwrites them from the drf plugin's attrs
    arr.job_drf_allocated = np.zeros((arr.job_min.shape[0], R),
                                     dtype=np.float32)
    arr.drf_total = np.zeros(R, dtype=np.float32)
    arr.job_drf_prerank = np.zeros(arr.job_min.shape[0], dtype=np.int32)

    arr.thresholds = vocab.thresholds()
    arr.scalar_dim_mask = np.zeros(R, dtype=bool)
    arr.scalar_dim_mask[2:] = True

    cache.sweep({j.uid for j in arr.jobs_list},
                {ni.name for ni in nodes_list}, sigs)
    return arr
