"""Snapshot flattening: ClusterInfo -> padded device arrays.

This is the TPU equivalent of the reference's parallel snapshot clone
(cache.go:693-742): each session the host flattens the cluster into
fixed-shape float32/int32 arrays (padded to compile buckets so XLA reuses
compiled executables across cycles) and ships them to the device in one
transfer. Mapping tables (tasks_list / nodes_list / jobs_list) translate
solver outputs back into TaskInfo/NodeInfo objects for Statement replay.

Predicate masks are precomputed host-side per unique constraint signature
(node selector + affinity + tolerations hash) so the device matrix is a
cheap gather: sig_masks[S, N] with S = number of distinct signatures, which
is tiny in practice even when T is 10k.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import (
    JobInfo, NodeInfo, NodePhase, Resource, ResourceVocab, TaskInfo,
    TaskStatus, MIN_MEMORY, MIN_MILLI_CPU, MIN_MILLI_SCALAR,
)

#: compile-bucket sizes: quarter-steps between powers of two, floor 8 —
#: keeps the number of distinct compiled shapes logarithmic in cluster size
#: while capping padding overhead at 25%
def bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        for frac in (1.25, 1.5, 1.75, 2.0):
            cand = int(b * frac)
            if cand >= n:
                return cand
        b *= 2
    return b


def _match_node_selector(selector: Dict[str, str], node) -> bool:
    labels = node.labels or {}
    return all(labels.get(k) == v for k, v in selector.items())


def taint_tolerated(taint: dict, tolerations: List[dict]) -> bool:
    for tol in tolerations or []:
        op = tol.get("operator", "Equal")
        if tol.get("key") and tol["key"] != taint.get("key"):
            continue
        if op == "Equal" and tol.get("value") != taint.get("value"):
            continue
        if tol.get("effect") and tol["effect"] != taint.get("effect"):
            continue
        return True
    return False


def _tolerates(tolerations: List[dict], node) -> bool:
    """NoSchedule/NoExecute taints must be tolerated (predicates plugin)."""
    for taint in node.taints or []:
        if taint.get("effect") not in ("NoSchedule", "NoExecute"):
            continue
        if not taint_tolerated(taint, tolerations):
            return False
    return True


def _node_affinity_match(affinity: Optional[dict], node) -> bool:
    """requiredDuringSchedulingIgnoredDuringExecution node affinity subset:
    matchExpressions with In/NotIn/Exists/DoesNotExist operators."""
    if not affinity:
        return True
    na = affinity.get("nodeAffinity") or {}
    req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not req:
        return True
    labels = node.labels or {}
    for term in req.get("nodeSelectorTerms", []):
        ok = True
        for expr in term.get("matchExpressions", []):
            key, op = expr.get("key"), expr.get("operator")
            vals = expr.get("values", [])
            has = key in labels
            if op == "In":
                ok &= has and labels[key] in vals
            elif op == "NotIn":
                ok &= not (has and labels[key] in vals)
            elif op == "Exists":
                ok &= has
            elif op == "DoesNotExist":
                ok &= not has
            if not ok:
                break
        if ok:
            return True  # terms are ORed
    return False


def _signature(task: TaskInfo) -> str:
    s = task.sig_cache
    if s is not None:
        return s
    pod = task.pod
    if not pod.node_selector and pod.affinity is None and not pod.tolerations:
        ports = pod.ports()
        if not ports:
            s = ""  # unconstrained fast path (the common case)
        else:
            s = json.dumps({"ports": sorted(ports)})
    else:
        s = json.dumps({
            "sel": sorted((pod.node_selector or {}).items()),
            "aff": pod.affinity,
            "tol": pod.tolerations,
            "ports": sorted(pod.ports()),
        }, sort_keys=True, default=str)
    task.sig_cache = s
    return s


@dataclass
class ScoreParams:
    """Scalar weights feeding the on-device scoring families. Plugins set
    these during OnSessionOpen (binpack/nodeorder register here instead of
    per-(task,node) Python callbacks)."""

    binpack_weight: float = 0.0
    binpack_res_weights: Optional[np.ndarray] = None  # [R]
    least_req_weight: float = 0.0
    most_req_weight: float = 0.0
    balanced_weight: float = 0.0
    # static per-node score added for every task (e.g. node-affinity
    # preferences evaluated host-side): [N]
    node_static: Optional[np.ndarray] = None

    def resolved(self, R: int, N: int) -> "ScoreParams":
        p = ScoreParams(
            binpack_weight=self.binpack_weight,
            least_req_weight=self.least_req_weight,
            most_req_weight=self.most_req_weight,
            balanced_weight=self.balanced_weight)
        p.binpack_res_weights = (
            np.ones(R, dtype=np.float32) if self.binpack_res_weights is None
            else np.asarray(self.binpack_res_weights, dtype=np.float32))
        p.node_static = (
            np.zeros(N, dtype=np.float32) if self.node_static is None
            else np.asarray(self.node_static, dtype=np.float32))
        return p


@dataclass
class SnapshotArrays:
    """Padded array view of one session's decision problem."""

    vocab: ResourceVocab
    # -- tasks (pending tasks of schedulable jobs, in scheduling order) -----
    tasks_list: List[TaskInfo] = field(default_factory=list)
    task_init_req: np.ndarray = None    # [T,R] launch request (fit check)
    task_req: np.ndarray = None         # [T,R] running request (accounting)
    task_job: np.ndarray = None         # [T] -> job index
    task_rank: np.ndarray = None        # [T] global priority order (0 first)
    task_sig: np.ndarray = None         # [T] -> signature index
    task_counts_ready: np.ndarray = None  # [T] bool: counts toward gang
    task_valid: np.ndarray = None       # [T] bool
    # -- jobs ----------------------------------------------------------------
    jobs_list: List[JobInfo] = field(default_factory=list)
    job_min: np.ndarray = None          # [J]
    job_ready_base: np.ndarray = None   # [J] ready_task_num at snapshot
    job_queue: np.ndarray = None        # [J] -> queue index
    job_valid: np.ndarray = None        # [J] bool
    # DRF ordering inputs (filled by the allocate action from the drf
    # plugin's session-open attrs; zeros when drf is inactive)
    job_drf_allocated: np.ndarray = None  # [J,R]
    drf_total: np.ndarray = None          # [R]
    #: static MAJOR ordering key for the in-kernel drf/hdrf re-rank: dense
    #: rank from the job-order providers that precede drf in the tiers
    #: (priority/gang) — live shares only break its ties, so a strict
    #: priority is never inverted by a share re-rank
    job_drf_prerank: np.ndarray = None    # [J] int32
    # hierarchical-DRF tree (ops.hdrf.build_hdrf; None unless hdrf active)
    hdrf_parent: np.ndarray = None        # [H]
    hdrf_weight: np.ndarray = None        # [H]
    hdrf_depth: np.ndarray = None         # [H]
    hdrf_is_leaf: np.ndarray = None       # [H] bool
    hdrf_leaf_req: np.ndarray = None      # [H,R]
    hdrf_job_leaf: np.ndarray = None      # [J]
    hdrf_ancestors: np.ndarray = None     # [J,D]
    hdrf_total_allocated: np.ndarray = None  # [R]
    # -- nodes ---------------------------------------------------------------
    nodes_list: List[NodeInfo] = field(default_factory=list)
    node_idle: np.ndarray = None        # [N,R]
    node_extra_future: np.ndarray = None  # [N,R] releasing - pipelined
    node_used: np.ndarray = None        # [N,R]
    node_alloc: np.ndarray = None       # [N,R] allocatable
    node_npods: np.ndarray = None       # [N]
    node_max_pods: np.ndarray = None    # [N]
    node_valid: np.ndarray = None       # [N] bool
    # -- predicate signatures ------------------------------------------------
    sig_masks: np.ndarray = None        # [S,N] bool
    # -- queues --------------------------------------------------------------
    queues_list: List[str] = field(default_factory=list)
    queue_weight: np.ndarray = None     # [Q] (0 = padded/absent queue)
    queue_capability: np.ndarray = None  # [Q,R] (inf where uncapped)
    queue_allocated: np.ndarray = None  # [Q,R]
    queue_request: np.ndarray = None    # [Q,R] allocated + pending requests
    # -- misc ----------------------------------------------------------------
    thresholds: np.ndarray = None       # [R]
    scalar_dim_mask: np.ndarray = None  # [R] bool: dims 2+ (ignorable)

    @property
    def T(self) -> int:
        return self.task_init_req.shape[0]

    @property
    def N(self) -> int:
        return self.node_idle.shape[0]

    @property
    def R(self) -> int:
        return self.task_init_req.shape[1]

    @property
    def J(self) -> int:
        return self.job_min.shape[0]

    def packed(self):
        """Pack the solver arrays into one f32 buffer + one i32 buffer so the
        per-session host->device transfer is two puts instead of ~20 (the
        per-transfer latency through the device tunnel dominates at small
        sizes). Returns (fbuf, ibuf, layout); feed to solve_allocate_packed.
        """
        d = self.device_dict()
        fparts, iparts, layout = [], [], []
        foff = ioff = 0
        for k in sorted(d):
            v = d[k]
            if v.dtype == np.float32:
                fparts.append(v.ravel())
                layout.append((k, "f", foff, v.size, v.shape))
                foff += v.size
            elif v.dtype == np.bool_:
                iparts.append(v.ravel().astype(np.int32))
                layout.append((k, "b", ioff, v.size, v.shape))
                ioff += v.size
            else:
                iparts.append(v.ravel().astype(np.int32))
                layout.append((k, "i", ioff, v.size, v.shape))
                ioff += v.size
        fbuf = np.concatenate(fparts) if fparts else np.zeros(0, np.float32)
        ibuf = np.concatenate(iparts) if iparts else np.zeros(0, np.int32)
        return fbuf, ibuf, tuple(layout)

    def fill_queue_demand(self) -> None:
        """Fill queue_request from the flattened jobs' total requests — a
        stand-in for the proportion plugin's session-open attrs when no
        session is in the loop (benches, dryruns, kernel-level tests).
        The allocate action overwrites these from the plugin instead."""
        self.queue_request[:] = 0.0
        for j, job in enumerate(self.jobs_list):
            self.queue_request[self.job_queue[j]] += \
                job.total_request.to_vector(self.vocab)

    def device_dict(self) -> Dict[str, np.ndarray]:
        """The arrays the solver kernel consumes (one host->device hop).
        hdrf arrays ride along only when the hierarchy was built (their
        presence changes the packed layout, i.e. compiles an hdrf
        variant)."""
        d = self._base_device_dict()
        if self.hdrf_parent is not None:
            d.update({
                "hdrf_parent": self.hdrf_parent,
                "hdrf_weight": self.hdrf_weight,
                "hdrf_depth": self.hdrf_depth,
                "hdrf_is_leaf": self.hdrf_is_leaf,
                "hdrf_leaf_req": self.hdrf_leaf_req,
                "hdrf_job_leaf": self.hdrf_job_leaf,
                "hdrf_ancestors": self.hdrf_ancestors,
                "hdrf_total_allocated": self.hdrf_total_allocated,
            })
        return d

    def _base_device_dict(self) -> Dict[str, np.ndarray]:
        return {
            "task_init_req": self.task_init_req,
            "task_req": self.task_req,
            "task_job": self.task_job,
            "task_rank": self.task_rank,
            "task_sig": self.task_sig,
            "task_counts_ready": self.task_counts_ready,
            "task_valid": self.task_valid,
            "job_min": self.job_min,
            "job_ready_base": self.job_ready_base,
            "job_queue": self.job_queue,
            "job_valid": self.job_valid,
            "job_drf_allocated": self.job_drf_allocated,
            "drf_total": self.drf_total,
            "job_drf_prerank": self.job_drf_prerank,
            "node_idle": self.node_idle,
            "node_extra_future": self.node_extra_future,
            "node_used": self.node_used,
            "node_alloc": self.node_alloc,
            "node_npods": self.node_npods,
            "node_max_pods": self.node_max_pods,
            "node_valid": self.node_valid,
            "sig_masks": self.sig_masks,
            "queue_weight": self.queue_weight,
            "queue_capability": self.queue_capability,
            "queue_allocated": self.queue_allocated,
            "queue_request": self.queue_request,
            "thresholds": self.thresholds,
            "scalar_dim_mask": self.scalar_dim_mask,
        }


class FlattenCache:
    """Incremental cross-session flatten state.

    The reference deep-clones the whole cluster every cycle (cache.go:693-742,
    one goroutine per job); the TPU build instead keeps the device-ready
    columns warm across sessions and recomputes only what changed, keyed on
    ``JobInfo.flat_version`` / ``NodeInfo.flat_version`` bumps. A cold cache
    (or ``cache=None``) reproduces the full flatten; results are identical
    either way because every entry is verified against the live objects'
    versions and task-uid sequences before reuse.

    The assembly itself is delta-driven: the padded task/job/node arrays
    are persistent buffers owned by the cache, and each flatten rewrites
    only the dirty rows — the job blocks outside the common prefix/suffix
    of the (key, version, len) job layout, and the node rows whose
    (name, epoch, flat_version) triple moved. An unchanged-snapshot cycle
    re-packs nothing; a 1%-churn cycle re-packs ~1% of the rows. The
    signature and queue index tables reuse the previous session's
    first-seen order whenever the dirty blocks' signature/queue sequences
    are unchanged, so the packed buffers stay byte-identical to a cold
    flatten (asserted across churn patterns by
    tests/test_solver.py::TestFlattenIncrementalIdentity).
    """

    def __init__(self, vocab: Optional[ResourceVocab] = None):
        self.vocab = vocab
        self.job_blocks: Dict[str, dict] = {}
        self.node_rows: Dict[str, dict] = {}
        self.sig_rows: Dict[str, tuple] = {}   # sig -> (node_key, row[N])
        self._node_key: Optional[tuple] = None
        self._node_buf: Optional[dict] = None
        #: previous task/job assembly: persistent padded buffers plus the
        #: per-position layout ((key, version, len) per job, uid sequence,
        #: per-block signature/queue sequences) the delta diff runs against
        self._asm: Optional[dict] = None
        #: cached spec-keyed signature tuple (rebuilt only when some node's
        #: spec actually changed — accounting churn must not pay for it)
        self._spec_key: Optional[tuple] = None
        # -- event-sourced flatten ledger (see enable_events) ---------------
        self.events_enabled = False
        self._ev_lock = threading.Lock()
        self._ev_feed = 0          # deltas the owner OBSERVED (pre-drop)
        self._ev_seq = 0           # deltas actually marked into the ledger
        self._ev_prev_feed = 0     # both counters as of the last flatten
        self._ev_prev_seq = 0
        self._ev_dirty_jobs: set = set()
        self._ev_dirty_nodes: set = set()
        self._ev_node_relayout = False  # node add/delete/readiness change
        self._ev_broken: Optional[str] = None  # unmapped delta seen
        self._ev_valid = False     # support structures exist & trustworthy
        self._evn: Optional[dict] = None  # event-path support structures
        #: per-flatten observability, read by the allocate action/scheduler
        self.last_flatten_mode = "cold"
        self.last_fallback_reason: Optional[str] = None
        self.last_rows_patched = 0
        self.last_events_applied = 0
        self.fallback_counts: Dict[str, int] = {}
        self._count_base = 0

    # -- event-sourced flatten ledger ---------------------------------------
    #
    # With events enabled, the owning mirror (SchedulerCache) forwards every
    # typed delta it applies — pod add/update/delete, node events, job/
    # podgroup events — via feed_event as it arrives, and the version-gated
    # snapshot-clone seam re-marks anything it re-cuts. flatten_snapshot
    # then starts from "the dirty rows ARE known" and patches exactly those
    # onto the persistent padded buffers instead of re-diffing the whole
    # snapshot: host cost O(events since last cycle), ~zero on a quiet
    # cluster. Consistency epoch: _ev_feed counts deltas observed, _ev_seq
    # deltas that actually landed in the ledger; a dropped or duplicated
    # delivery skews them apart, the next flatten detects the skew and
    # falls back to the full re-diff (which trusts nothing), so a broken
    # feed degrades to the PR-1 incremental path, never to a wrong layout.

    def enable_events(self) -> None:
        """Opt this cache into the event-sourced flatten. The owner MUST
        then feed every mirror delta through feed_event (directly or via
        the snapshot-clone seam); unfed mutations void the byte-identity
        guarantee of the event fast path."""
        self.events_enabled = True

    def feed_event(self, kind: str, event: str, job: Optional[str] = None,
                   node: Optional[str] = None) -> None:
        """Record one typed mirror delta. kind: pod|node|job|queue|resync;
        ``job`` is the flatten job key (JobInfo.uid), ``node`` the node
        name. Unknown kinds conservatively invalidate the ledger."""
        if not self.events_enabled:
            return
        from ..resilience.faultinject import faults
        with self._ev_lock:
            self._ev_feed += 1
        try:
            # chaos seam: an armed `flatten_event` drops this delta on the
            # floor exactly as a torn mirror feed would — the feed counter
            # already moved, the ledger mark below never lands, and the
            # epoch check catches the skew at the next flatten
            faults.fire("flatten_event")
        except Exception:  # noqa: BLE001 — the drop IS the fault
            return
        self._apply_mark(kind, event, job, node)
        try:
            # `flatten_event_dup`: the same delta delivered twice
            faults.fire("flatten_event_dup")
        except Exception:  # noqa: BLE001
            self._apply_mark(kind, event, job, node)

    def _apply_mark(self, kind: str, event: str, job: Optional[str],
                    node: Optional[str]) -> None:
        with self._ev_lock:
            self._ev_seq += 1
            if kind == "pod":
                if job:
                    self._ev_dirty_jobs.add(job)
                if node:
                    self._ev_dirty_nodes.add(node)
            elif kind == "node":
                if event in ("add", "delete"):
                    # membership/position change: the padded node axis
                    # relays out, which only the full diff handles
                    self._ev_node_relayout = True
                if node:
                    self._ev_dirty_nodes.add(node)
            elif kind in ("job", "podgroup"):
                if job:
                    self._ev_dirty_jobs.add(job)
            elif kind == "queue":
                pass  # queue tables rebuild from the queues dict per cycle
            else:
                self._ev_broken = f"unmapped:{kind}"

    def suppress_event_path(self, reason: str) -> None:
        """Decline the event fast path at the next flatten (the full
        re-diff runs instead). For callers that mutated flatten inputs
        outside the ledger's sight — e.g. a session whose conf ran
        mutating actions before allocate."""
        with self._ev_lock:
            self._ev_broken = reason

    def _ev_take(self) -> dict:
        """Atomically snapshot the ledger at flatten start. Marks arriving
        DURING the flatten belong to the next cycle and stay queued."""
        with self._ev_lock:
            return {
                "feed": self._ev_feed, "seq": self._ev_seq,
                "jobs": set(self._ev_dirty_jobs),
                "nodes": set(self._ev_dirty_nodes),
                "relayout": self._ev_node_relayout,
                "broken": self._ev_broken,
            }

    def _ev_commit(self, taken: dict, mode: str,
                   reason: Optional[str], rows_patched: int) -> None:
        """Consume the taken ledger snapshot after a successful flatten of
        EITHER path (the full re-diff revalidates everything, so its result
        subsumes any marks it consumed) and re-baseline the epoch."""
        with self._ev_lock:
            self._ev_dirty_jobs -= taken["jobs"]
            self._ev_dirty_nodes -= taken["nodes"]
            if self._ev_feed == taken["feed"]:
                # no concurrent marks: structural flags are fully consumed;
                # otherwise leave them set so the next cycle re-diffs
                self._ev_node_relayout = False
                self._ev_broken = None
            self._ev_prev_feed = taken["feed"]
            self._ev_prev_seq = taken["seq"]
            self._ev_valid = True
        self.last_flatten_mode = mode
        self.last_fallback_reason = reason
        self.last_rows_patched = rows_patched
        self.last_events_applied = taken["feed"] - self._count_base
        self._count_base = taken["feed"]
        if reason is not None:
            self.fallback_counts[reason] = \
                self.fallback_counts.get(reason, 0) + 1

    # -- per-node rows ------------------------------------------------------

    def node_row(self, ni: NodeInfo) -> dict:
        vocab = self.vocab
        R = len(vocab)
        ent = self.node_rows.get(ni.name)
        if ent is not None and ent["v"] == ni.flat_version \
                and ent["e"] == ni.flat_epoch and ent["R"] == R:
            return ent
        idle = ni.idle.to_vector(vocab)
        used = ni.used.to_vector(vocab)
        extra = ni.releasing.to_vector(vocab) - ni.pipelined.to_vector(vocab)
        alloc = ni.allocatable.to_vector(vocab)
        alloc = np.where(alloc > 0, alloc, 1.0).astype(np.float32)
        npods = sum(1 for t in ni.tasks.values()
                    if t.status != TaskStatus.PIPELINED)
        ent = {"v": ni.flat_version, "e": ni.flat_epoch, "R": R,
               "sv": ni.spec_version,
               "idle": idle, "used": used,
               "extra": extra, "alloc": alloc, "npods": npods,
               "maxp": ni.allocatable.max_task_num or 1 << 30}
        self.node_rows[ni.name] = ent
        return ent

    # -- per-job task blocks ------------------------------------------------

    def job_block(self, job: JobInfo, tasks: List[TaskInfo],
                  uids: List[str]) -> dict:
        vocab = self.vocab
        R = len(vocab)
        ent = self.job_blocks.get(job.uid)
        if (ent is not None and ent["v"] == job.flat_version
                and ent["R"] == R and ent["uids"] == uids):
            return ent
        k = len(tasks)
        # bulk cpu/mem extraction: one list-comprehension + np.array beats
        # 2k per-task to_vector calls ~5x (the all-cold burst flatten is
        # this loop); scalar resources overlay the rare rows after
        init = np.zeros((k, R), dtype=np.float32)
        req = np.zeros((k, R), dtype=np.float32)
        init[:, :2] = np.array(
            [(t.init_resreq.milli_cpu, t.init_resreq.memory)
             for t in tasks], dtype=np.float32).reshape(k, 2)
        req[:, :2] = np.array(
            [(t.resreq.milli_cpu, t.resreq.memory)
             for t in tasks], dtype=np.float32).reshape(k, 2)
        any_scalar = np.zeros(k, dtype=bool)
        for i, t in enumerate(tasks):
            if t.init_resreq.scalars or t.resreq.scalars:
                for name, v in t.init_resreq.scalars.items():
                    if v >= MIN_MILLI_SCALAR:
                        # vocab-independent, like Resource.is_empty
                        any_scalar[i] = True
                    idx = vocab.index(name)
                    if idx is not None:
                        init[i, idx] = v
                for name, v in t.resreq.scalars.items():
                    idx = vocab.index(name)
                    if idx is not None:
                        req[i, idx] = v
        # not is_empty(): the api.resource thresholds
        counts = ((init[:, 0] >= MIN_MILLI_CPU)
                  | (init[:, 1] >= MIN_MEMORY) | any_scalar)
        sig_uniq: List[str] = []
        sig_reps: List[TaskInfo] = []
        sig_idx: Dict[str, int] = {}
        sig_local = np.zeros(k, dtype=np.int32)
        for i, t in enumerate(tasks):
            s = _signature(t)
            li = sig_idx.get(s)
            if li is None:
                li = sig_idx[s] = len(sig_uniq)
                sig_uniq.append(s)
                sig_reps.append(t)
            sig_local[i] = li
        ent = {"v": job.flat_version, "R": R, "uids": uids,
               "init": init, "req": req, "counts": counts,
               "sig_uniq": sig_uniq, "sig_reps": sig_reps,
               "sig_local": sig_local, "min": job.min_available,
               "ready": job.ready_task_num(), "queue": job.queue}
        self.job_blocks[job.uid] = ent
        return ent

    # -- bounded size -------------------------------------------------------

    def sweep(self, jobs_list, nodes_list, live_sigs) -> None:
        """Drop entries for departed jobs/nodes/signatures once the maps grow
        well past the live set, so a churny cluster can't grow the cache
        unboundedly (job blocks pin task arrays and Pod refs). The live sets
        are built lazily — in steady state only the size checks run."""
        if len(self.job_blocks) > 2 * len(jobs_list) + 64:
            live_jobs = {j.uid for j in jobs_list}
            self.job_blocks = {k: v for k, v in self.job_blocks.items()
                               if k in live_jobs}
        if len(self.node_rows) > 2 * len(nodes_list) + 64:
            live_nodes = {ni.name for ni in nodes_list}
            self.node_rows = {k: v for k, v in self.node_rows.items()
                              if k in live_nodes}
        if len(self.sig_rows) > 2 * len(live_sigs) + 64:
            self.sig_rows = {k: v for k, v in self.sig_rows.items()
                             if k in live_sigs}

    # -- vocab growth -------------------------------------------------------

    def ensure_names(self, resources) -> None:
        """Register any new scalar resource names (vocab only ever grows, so
        previously cached entries stay valid names-wise; width changes are
        caught by the per-entry R check)."""
        vocab = self.vocab
        for r in resources:
            for name in r.scalars:
                if vocab.index(name) is None:
                    vocab.add(name)


def flatten_snapshot(
    jobs: Dict[str, JobInfo],
    nodes: Dict[str, NodeInfo],
    tasks_in_order: List[TaskInfo],
    vocab: Optional[ResourceVocab] = None,
    queues: Optional[Dict[str, object]] = None,
    cache: Optional[FlattenCache] = None,
    grouped: Optional[List[tuple]] = None,
) -> SnapshotArrays:
    """Flatten session state into padded arrays.

    tasks_in_order: the pending tasks to place, already sorted by the
    session's namespace/queue/job/task ordering (host-side comparator pass —
    the ordering semantics stay in Python, the math goes on device).
    Tasks must be grouped by job within the order.

    Pass a persistent ``cache`` (the SchedulerCache owns one) to make the
    per-session flatten incremental: unchanged jobs reuse their cached task
    blocks, unchanged nodes their rows.

    NOTE: with a persistent cache the returned arrays alias cache-owned
    buffers that the NEXT flatten call may rewrite in place — they are valid
    for the current session only. Callers that need to retain arrays across
    sessions must copy them.
    """
    if cache is None:
        cache = FlattenCache(vocab)
    elif vocab is not None and cache.vocab is None:
        cache.vocab = vocab
    if cache.vocab is None:
        resources = []
        for ni in nodes.values():
            resources.append(ni.allocatable)
        for t in tasks_in_order:
            resources.append(t.init_resreq)
        cache.vocab = ResourceVocab.collect(resources)
    vocab = cache.vocab

    n_tasks = len(tasks_in_order)

    # group tasks by job, preserving order (callers that already hold the
    # per-job grouping — the allocate action — pass it via `grouped` and
    # skip this O(T) pass)
    jobs_seq = None
    if grouped is not None:
        job_keys = [j.uid for j, _ in grouped]
        job_tasks = [ts for _, ts in grouped]
        jobs_seq = [j for j, _ in grouped]
    else:
        job_keys: List[str] = []
        job_tasks: List[List[TaskInfo]] = []
        cur = None
        cur_list: List[TaskInfo] = []
        for t in tasks_in_order:
            if t.job != cur:
                cur = t.job
                cur_list = []
                job_keys.append(cur)
                job_tasks.append(cur_list)
            cur_list.append(t)
        if len(set(job_keys)) != len(job_keys):
            # non-contiguous job grouping (callers should not do this, the
            # sequential solver depends on contiguity): merge defensively
            merged: Dict[str, List[TaskInfo]] = {}
            for k, ts in zip(job_keys, job_tasks):
                merged.setdefault(k, []).extend(ts)
            job_keys = list(merged)
            job_tasks = list(merged.values())
            tasks_in_order = [t for ts in job_tasks for t in ts]
            n_tasks = len(tasks_in_order)

    if jobs_seq is None:
        jobs_seq = [jobs[k] for k in job_keys]

    # -- event-sourced fast path --------------------------------------------
    # With a fed ledger (cache.enable_events + feed_event) a cycle whose
    # deltas all map onto existing rows skips EVERY per-job/per-node scan
    # below: validate the consistency epoch, patch exactly the dirty rows,
    # reuse the previous assembly. Anything structural (layout shift, node
    # relayout, vocab growth, epoch skew) declines into the full re-diff
    # below, which trusts nothing — the event -> incremental -> cold ladder.
    taken = ev_reason = None
    if cache.events_enabled:
        taken = cache._ev_take()
        arr, ev_reason = _flatten_event(
            cache, jobs, nodes, tasks_in_order, queues,
            job_keys, job_tasks, jobs_seq, taken)
        if arr is not None:
            return arr

    # inline the ready check (state.phase is a slot read; the property call
    # costs ~0.2us x N on the per-cycle floor)
    _ready = NodePhase.READY
    nodes_list = [n for n in nodes.values() if n.state.phase is _ready]
    n_nodes = len(nodes_list)

    versions = [j.flat_version for j in jobs_seq]
    lens = [len(ts) for ts in job_tasks]
    nJ = len(job_keys)

    # -- delta diff against the previous assembly ---------------------------
    # P jobs of common prefix and S of common suffix (key, version and task
    # count all matching) frame the dirty middle; with ~1% churn the middle
    # is a handful of job blocks, and only those are re-packed below
    asm = cache._asm
    if asm is not None:
        ok_, ov_, ol_ = asm["job_keys"], asm["versions"], asm["lens"]
        oJ = len(ok_)
        if job_keys == ok_ and versions == ov_ and lens == ol_:
            P, S = nJ, 0  # unchanged layout: one C-speed compare, no walk
        else:
            m = min(nJ, oJ)
            P = 0
            while P < m and job_keys[P] == ok_[P] \
                    and versions[P] == ov_[P] and lens[P] == ol_[P]:
                P += 1
            S = 0
            lim = m - P
            while S < lim and job_keys[nJ - 1 - S] == ok_[oJ - 1 - S] \
                    and versions[nJ - 1 - S] == ov_[oJ - 1 - S] \
                    and lens[nJ - 1 - S] == ol_[oJ - 1 - S]:
                S += 1
        # verify the reusable regions' task identity: the caller passing
        # the same task-list OBJECT (the steady grouped path) certifies
        # the sequence unchanged for free; fresh lists fall back to a
        # per-job uid compare (C speed; version alone is trusted nowhere,
        # matching job_block). Callers must not reorder or mutate a task
        # list in place once handed to a flatten — build a new list.
        tl = asm["task_lists"]
        tu = asm["task_uids"]
        for j in range(P):
            ts = job_tasks[j]
            if ts is tl[j]:
                continue
            if [t.uid for t in ts] != tu[j]:
                P = j
                break
        if nJ != oJ or n_tasks != asm["n_tasks"]:
            # job positions / task offsets shift: the suffix cannot be
            # reused in place, rewrite everything from the prefix on
            S = 0
        for k2 in range(S):
            j = nJ - 1 - k2
            ts = job_tasks[j]
            if ts is tl[oJ - 1 - k2]:
                continue
            if [t.uid for t in ts] != tu[oJ - 1 - k2]:
                S = k2
                break
        off_P = sum(lens[:P])
    else:
        oJ = 0
        P = S = 0
        off_P = 0

    # vocab growth pre-pass: only entries about to recompute can introduce
    # new names; scanning just those (dirty-middle jobs, changed nodes)
    # keeps R stable below at O(churn) cost
    for j in range(P, nJ - S):
        ent = cache.job_blocks.get(job_keys[j])
        if ent is None or ent["v"] != versions[j]:
            cache.ensure_names(t.init_resreq for t in job_tasks[j])
            cache.ensure_names(t.resreq for t in job_tasks[j])
    # node layout key: parallel (epochs, versions) int arrays instead of a
    # tuple-of-triples — flat_epoch is unique per NodeInfo instance, so it
    # IS the position identity (names are only read for the rows that
    # actually recompute), and the dirty scan is two numpy != reductions
    node_epochs = np.array([ni.flat_epoch for ni in nodes_list],
                           dtype=np.int64)
    node_vers = np.array([ni.flat_version for ni in nodes_list],
                         dtype=np.int64)
    node_key = (node_epochs, node_vers)
    old_nk = cache._node_key
    if old_nk is not None and old_nk[0].shape[0] == n_nodes:
        dirty = np.nonzero((node_epochs != old_nk[0])
                           | (node_vers != old_nk[1]))[0].tolist()
    else:
        dirty = None  # resized/relaid layout: every row dirty
    rows = cache.node_rows
    for i in (dirty if dirty is not None else range(n_nodes)):
        ni = nodes_list[i]
        ent = rows.get(ni.name)
        if ent is None or ent["v"] != ni.flat_version:
            cache.ensure_names((ni.allocatable,))
    R = len(vocab)

    N = bucket(max(n_nodes, 1))
    T = bucket(max(n_tasks, 1))
    # +1 guarantees a padded (invalid) job slot: padded tasks point there so
    # the sequential solver's job-boundary logic never revisits a real job
    J = bucket(nJ + 1)
    shape_key = (R, T, J)

    arr = SnapshotArrays(vocab=vocab)
    arr.tasks_list = list(tasks_in_order)
    arr.nodes_list = nodes_list
    arr.jobs_list = jobs_seq

    # -- task/job side: persistent padded buffers, rewrite dirty rows only --
    if asm is not None and asm["shape"] != shape_key:
        asm = None
        P = S = 0
        oJ = 0
        off_P = 0
    if asm is not None:
        flat_mode = "incremental"
        bufs = asm["bufs"]
        blocks_list = asm["blocks"]
        mid_blocks = []
        mid_uids = []
        off = off_P
        for j in range(P, nJ - S):
            k = lens[j]
            u = [t.uid for t in job_tasks[j]]
            mid_uids.append(u)
            ent = cache.job_block(jobs_seq[j], job_tasks[j], u)
            mid_blocks.append(ent)
            if k:
                bufs["init"][off:off + k] = ent["init"]
                bufs["req"][off:off + k] = ent["req"]
                bufs["counts"][off:off + k] = ent["counts"]
            off += k
        end_mid = off
        if nJ - S > P:
            bufs["task_job"][off_P:end_mid] = np.repeat(
                np.arange(P, nJ - S, dtype=np.int32),
                np.asarray(lens[P:nJ - S], dtype=np.int64))
        jmin, jready = bufs["job_min"], bufs["job_ready"]
        jvalid = bufs["job_valid"]
        for j in range(P, nJ - S):
            ent = mid_blocks[j - P]
            jmin[j] = ent["min"]
            jready[j] = ent["ready"]
            jvalid[j] = True
        if S == 0:
            # shape is unchanged but counts may differ: restore the padding
            # invariants (rows >= n_tasks all-zero / invalid / padded-job)
            old_n = asm["n_tasks"]
            if old_n > n_tasks:
                bufs["init"][n_tasks:old_n] = 0.0
                bufs["req"][n_tasks:old_n] = 0.0
                bufs["counts"][n_tasks:old_n] = False
                bufs["sig"][n_tasks:old_n] = 0
            bufs["task_job"][n_tasks:] = J - 1
            bufs["valid"][:n_tasks] = True
            bufs["valid"][n_tasks:] = False
            if oJ > nJ:
                jmin[nJ:oJ] = 0
                jready[nJ:oJ] = 0
                jvalid[nJ:oJ] = False
                bufs["job_queue"][nJ:oJ] = 0

        # queue table: first-seen order over job blocks — unchanged when
        # the dirty middle's queue sequence is unchanged (the common case)
        new_queues = [b["queue"] for b in mid_blocks]
        old_mid_q = asm["job_queues"][P:oJ - S]
        asm["job_queues"][P:oJ - S] = new_queues
        if new_queues != old_mid_q:
            _rebuild_queue_table(asm, bufs)

        # signature table: same first-seen-order argument — if the middle's
        # per-block signature sequence is unchanged, the global table (and
        # every prefix/suffix task_sig row) is unchanged; only the middle
        # rows re-map through the existing table
        new_sig_seq = [b["sig_uniq"] for b in mid_blocks]
        old_mid_sigs = asm["block_sigs"][P:oJ - S]
        asm["block_sigs"][P:oJ - S] = new_sig_seq
        blocks_list[P:oJ - S] = mid_blocks
        if new_sig_seq == old_mid_sigs:
            sigs = asm["sigs"]
            sig_buf = bufs["sig"]
            off = off_P
            for i2, ent in enumerate(mid_blocks):
                k = lens[P + i2]
                if k:
                    uniq = ent["sig_uniq"]
                    if len(uniq) == 1:
                        sig_buf[off:off + k] = sigs[uniq[0]]
                    else:
                        remap = np.array([sigs[s] for s in uniq], np.int32)
                        sig_buf[off:off + k] = remap[ent["sig_local"]]
                off += k
        else:
            asm["sigs"], asm["sig_tasks"] = _rebuild_sigs(
                blocks_list, lens, bufs["sig"], n_tasks)
        asm["task_uids"][P:oJ - S] = mid_uids
        asm["task_lists"] = job_tasks
        asm["job_keys"] = job_keys
        asm["versions"] = versions
        asm["lens"] = lens
        asm["n_tasks"] = n_tasks
    else:
        # cold / reshaped: full assembly into fresh persistent buffers
        flat_mode = "cold"
        bufs = {
            "init": np.zeros((T, R), dtype=np.float32),
            "req": np.zeros((T, R), dtype=np.float32),
            "counts": np.zeros(T, dtype=bool),
            "sig": np.zeros(T, dtype=np.int32),
            "task_job": np.full(T, J - 1, dtype=np.int32),
            "rank": np.arange(T, dtype=np.int32),
            "valid": np.zeros(T, dtype=bool),
            "job_min": np.zeros(J, dtype=np.int32),
            "job_ready": np.zeros(J, dtype=np.int32),
            "job_queue": np.zeros(J, dtype=np.int32),
            "job_valid": np.zeros(J, dtype=bool),
        }
        blocks_list = []
        task_uids = []
        off = 0
        for j in range(nJ):
            k = lens[j]
            u = [t.uid for t in job_tasks[j]]
            task_uids.append(u)
            ent = cache.job_block(jobs_seq[j], job_tasks[j], u)
            blocks_list.append(ent)
            if k:
                bufs["init"][off:off + k] = ent["init"]
                bufs["req"][off:off + k] = ent["req"]
                bufs["counts"][off:off + k] = ent["counts"]
            off += k
        if n_tasks:
            bufs["task_job"][:n_tasks] = np.repeat(
                np.arange(nJ, dtype=np.int32),
                np.asarray(lens, dtype=np.int64))
            bufs["valid"][:n_tasks] = True
        queue_index: Dict[str, int] = {}
        queue_names: List[str] = []
        job_queues: List[str] = []
        jq = bufs["job_queue"]
        for j, ent in enumerate(blocks_list):
            bufs["job_min"][j] = ent["min"]
            bufs["job_ready"][j] = ent["ready"]
            bufs["job_valid"][j] = True
            q = ent["queue"]
            job_queues.append(q)
            qi = queue_index.get(q)
            if qi is None:
                qi = queue_index[q] = len(queue_names)
                queue_names.append(q)
            jq[j] = qi
        sigs, sig_tasks = _rebuild_sigs(blocks_list, lens, bufs["sig"],
                                        n_tasks)
        asm = {
            "shape": shape_key, "bufs": bufs, "blocks": blocks_list,
            "job_keys": job_keys, "versions": versions, "lens": lens,
            "task_uids": task_uids, "task_lists": job_tasks,
            "n_tasks": n_tasks,
            "block_sigs": [b["sig_uniq"] for b in blocks_list],
            "job_queues": job_queues,
            "sigs": sigs, "sig_tasks": sig_tasks,
            "queue_index": queue_index, "queue_names": queue_names,
        }
        cache._asm = asm

    arr.task_init_req = bufs["init"]
    arr.task_req = bufs["req"]
    arr.task_counts_ready = bufs["counts"]
    arr.task_sig = bufs["sig"]
    arr.task_job = bufs["task_job"]
    arr.task_rank = bufs["rank"]
    arr.task_valid = bufs["valid"]
    arr.job_min = bufs["job_min"]
    arr.job_ready_base = bufs["job_ready"]
    arr.job_queue = bufs["job_queue"]
    arr.job_valid = bufs["job_valid"]
    arr = _finish(arr, cache, nodes_list, n_nodes, R, N, node_key, dirty,
                  asm["sigs"], asm["sig_tasks"], asm["queue_index"],
                  asm["queue_names"], queues)
    cache.last_flatten_mode = flat_mode
    if taken is not None:
        # rebuild the event-path support structures against the fresh
        # assembly, then consume the ledger snapshot: the full re-diff
        # re-verified everything, so its marks are subsumed either way
        _ev_refresh(cache, arr, nodes, nodes_list, job_keys, lens)
        cache._ev_commit(taken, flat_mode, ev_reason, 0)
    return arr


def _rebuild_sigs(blocks_list, lens, sig_buf, n_tasks):
    """Full signature-table rebuild: global first-seen indices over the
    blocks in assembly order, task_sig rows written in place. The slow path
    — the delta flatten takes it only when a dirty block changes the
    per-block signature sequence."""
    sigs: Dict[str, int] = {}
    sig_tasks: List[TaskInfo] = []
    off = 0
    for j, ent in enumerate(blocks_list):
        k = lens[j]
        uniq = ent["sig_uniq"]
        remap = np.empty(max(len(uniq), 1), dtype=np.int32)
        for li, s in enumerate(uniq):
            gi = sigs.get(s)
            if gi is None:
                gi = sigs[s] = len(sig_tasks)
                sig_tasks.append(ent["sig_reps"][li])
            remap[li] = gi
        if k:
            sig_buf[off:off + k] = remap[ent["sig_local"]]
        off += k
    sig_buf[n_tasks:] = 0
    return sigs, sig_tasks


def _rebuild_queue_table(asm, bufs) -> None:
    """Queue index/name tables: global first-seen order over the per-job
    queue sequence, job_queue rows rewritten in place. Runs only when some
    rewritten block changed the queue sequence."""
    queue_index: Dict[str, int] = {}
    queue_names: List[str] = []
    jq = bufs["job_queue"]
    for j, q in enumerate(asm["job_queues"]):
        qi = queue_index.get(q)
        if qi is None:
            qi = queue_index[q] = len(queue_names)
            queue_names.append(q)
        jq[j] = qi
    asm["queue_index"] = queue_index
    asm["queue_names"] = queue_names


def _fill_sig_masks(cache, out, sigs, sig_tasks, nodes_list, spec_key,
                    acct_key, N: int) -> None:
    """Fill the [S, N] predicate mask rows from the per-signature row cache
    (recomputing rows whose key moved). Shared by the full flatten and the
    event path's refresh-after-node-churn."""
    for s, s_idx in sigs.items():
        # (even the unconstrained "" signature must run the node loop:
        # untolerated NoSchedule taints block constraint-free pods too)
        row_key = acct_key if sig_tasks[s_idx].pod.ports() else spec_key
        cached = cache.sig_rows.get(s)
        if cached is not None and cached[0] == row_key \
                and cached[1].shape[0] == N:
            out[s_idx] = cached[1]
            continue
        pod = sig_tasks[s_idx].pod
        row = np.zeros(N, dtype=bool)
        for n_idx, ni in enumerate(nodes_list):
            node = ni.node
            ok = True
            if node is not None:
                ok = (_match_node_selector(pod.node_selector or {}, node)
                      and _tolerates(pod.tolerations, node)
                      and _node_affinity_match(pod.affinity, node))
                if ok and pod.ports():
                    taken = set()
                    for other in ni.tasks.values():
                        taken.update(other.pod.ports())
                    ok = not (set(pod.ports()) & taken)
            row[n_idx] = ok
        cache.sig_rows[s] = (row_key, row)
        out[s_idx] = row


def _apply_queue_overrides(arr, queue_index, queues, vocab) -> None:
    """Overlay weight/capability from the session's queue objects onto the
    default-initialized queue tables."""
    if not queues:
        return
    for name, q_idx in queue_index.items():
        qi = queues.get(name)
        if qi is None:
            continue
        arr.queue_weight[q_idx] = getattr(qi, "weight", 1) or 1
        cap = getattr(qi, "capability", None)
        if cap:
            cap_vec = Resource.from_resource_list(cap).to_vector(vocab)
            arr.queue_capability[q_idx] = np.where(
                cap_vec > 0, cap_vec, np.inf)


def _ev_refresh(cache, arr, nodes, nodes_list, job_keys, lens) -> None:
    """Rebuild the event path's support structures after a full flatten:
    position maps, per-job buffer offsets, and references to the buffers
    the finish-lite pass reuses. Only runs for event-enabled caches, so
    plain caches pay nothing."""
    asm = cache._asm
    asm["job_pos"] = {k: i for i, k in enumerate(job_keys)}
    offs = np.zeros(len(lens) + 1, dtype=np.int64)
    if lens:
        np.cumsum(np.asarray(lens, dtype=np.int64), out=offs[1:])
    asm["offsets"] = offs
    cache._evn = {
        "arr": arr,
        "nodes_list": nodes_list,
        "node_pos": {ni.name: i for i, ni in enumerate(nodes_list)},
        "n_total": len(nodes),
        "N": cache._node_buf["N"],
        "queue_bufs": (arr.queue_weight, arr.queue_capability,
                       arr.queue_allocated, arr.queue_request),
        "drf_alloc": arr.job_drf_allocated,
        "drf_total": arr.drf_total,
        "drf_prerank": arr.job_drf_prerank,
    }


def _flatten_event(cache, jobs, nodes, tasks_in_order, queues,
                   job_keys, job_tasks, jobs_seq, taken):
    """The event-sourced assembly: patch exactly the ledger-marked rows
    onto the persistent padded buffers and reuse the previous SnapshotArrays
    object. Returns (arr, None) on success or (None, reason) to decline
    into the full re-diff. Byte-identity contract: given a completely fed
    ledger, the returned buffers are bit-identical to a cold flatten of the
    same inputs (tests/test_solver.py::TestFlattenEventIdentity)."""
    asm = cache._asm
    evn = cache._evn
    if asm is None or evn is None or not cache._ev_valid:
        return None, "no_assembly"
    if taken["broken"]:
        return None, taken["broken"]
    if (taken["feed"] - cache._ev_prev_feed) \
            != (taken["seq"] - cache._ev_prev_seq):
        # the consistency epoch: a delta was observed but never marked (or
        # marked twice) — the ledger cannot be trusted for this cycle
        return None, "epoch_mismatch"
    if taken["relayout"]:
        return None, "node_relayout"
    if len(nodes) != evn["n_total"]:
        return None, "node_membership"
    n_tasks = asm["n_tasks"]
    if len(tasks_in_order) != n_tasks or n_tasks == 0:
        return None, "task_count"
    if job_keys != asm["job_keys"]:
        # pending-set membership or job order shifted: block offsets move,
        # which is the prefix/suffix diff's territory
        return None, "job_layout"
    vocab = cache.vocab
    R, T, J = asm["shape"]
    buf = cache._node_buf
    if buf is None or buf["R"] != R or buf["N"] != evn["N"]:
        return None, "node_buf"

    lens = asm["lens"]
    job_pos = asm["job_pos"]
    dirty_jobs = []
    for uid in taken["jobs"]:
        j = job_pos.get(uid)
        if j is None:
            continue  # churned job not in this cycle's pending problem
        if len(job_tasks[j]) != lens[j]:
            return None, "task_count"
        dirty_jobs.append(j)
    nodes_list = evn["nodes_list"]
    node_pos = evn["node_pos"]
    _ready = NodePhase.READY
    dirty_nodes = []
    for name in taken["nodes"]:
        i = node_pos.get(name)
        ni = nodes.get(name)
        if i is None:
            if ni is not None and ni.state.phase is _ready:
                # became schedulable without an add event reaching us
                return None, "node_membership"
            continue  # dirtied node not part of the padded problem
        if ni is None or ni.state.phase is not _ready:
            return None, "node_membership"
        dirty_nodes.append((i, ni))

    # vocab growth pre-pass over exactly the dirty entries; new resource
    # names widen R, which re-lays out every padded buffer
    for j in dirty_jobs:
        ent = cache.job_blocks.get(job_keys[j])
        if ent is None or ent["v"] != jobs_seq[j].flat_version:
            cache.ensure_names(t.init_resreq for t in job_tasks[j])
            cache.ensure_names(t.resreq for t in job_tasks[j])
    for _, ni in dirty_nodes:
        cache.ensure_names((ni.allocatable,))
    if len(vocab) != R:
        return None, "vocab_growth"

    # -- patch dirty job blocks in place ------------------------------------
    bufs = asm["bufs"]
    offsets = asm["offsets"]
    blocks_list = asm["blocks"]
    rows_patched = 0
    sig_rebuild = False
    queue_rebuild = False
    dirty_jobs.sort()
    for j in dirty_jobs:
        ts = job_tasks[j]
        k = lens[j]
        u = [t.uid for t in ts]
        ent = cache.job_block(jobs_seq[j], ts, u)
        off = int(offsets[j])
        if k:
            bufs["init"][off:off + k] = ent["init"]
            bufs["req"][off:off + k] = ent["req"]
            bufs["counts"][off:off + k] = ent["counts"]
        rows_patched += k
        bufs["job_min"][j] = ent["min"]
        bufs["job_ready"][j] = ent["ready"]
        blocks_list[j] = ent
        asm["versions"][j] = jobs_seq[j].flat_version
        asm["task_uids"][j] = u
        if ent["queue"] != asm["job_queues"][j]:
            asm["job_queues"][j] = ent["queue"]
            queue_rebuild = True
        if ent["sig_uniq"] != asm["block_sigs"][j]:
            asm["block_sigs"][j] = ent["sig_uniq"]
            sig_rebuild = True
        elif k:
            sigs_map = asm["sigs"]
            uniq = ent["sig_uniq"]
            if len(uniq) == 1:
                bufs["sig"][off:off + k] = sigs_map[uniq[0]]
            else:
                remap = np.array([sigs_map[s] for s in uniq], np.int32)
                bufs["sig"][off:off + k] = remap[ent["sig_local"]]
    asm["task_lists"] = job_tasks
    asm["job_keys"] = job_keys
    if queue_rebuild:
        _rebuild_queue_table(asm, bufs)
    if sig_rebuild:
        asm["sigs"], asm["sig_tasks"] = _rebuild_sigs(
            blocks_list, lens, bufs["sig"], n_tasks)

    # -- patch dirty node rows in place -------------------------------------
    node_key = cache._node_key
    rows = cache.node_rows
    spec_stale = False
    patched_nodes = 0
    for i, ni in dirty_nodes:
        if node_key[0][i] == ni.flat_epoch \
                and node_key[1][i] == ni.flat_version:
            if nodes_list[i] is not ni:
                nodes_list[i] = ni  # re-cut clone with identical content
            continue
        if node_key[0][i] != ni.flat_epoch:
            # same position, different NodeInfo identity without an
            # add/delete event: don't guess, re-diff
            return None, "node_epoch"
        old = rows.get(ni.name)
        if old is None or old["sv"] != ni.spec_version:
            spec_stale = True
        row = cache.node_row(ni)
        buf["idle"][i] = row["idle"]
        buf["extra"][i] = row["extra"]
        buf["used"][i] = row["used"]
        buf["alloc"][i] = row["alloc"]
        buf["npods"][i] = row["npods"]
        buf["maxp"][i] = row["maxp"]
        node_key[1][i] = ni.flat_version
        nodes_list[i] = ni
        patched_nodes += 1
        rows_patched += 1
    if spec_stale:
        cache._spec_key = tuple((ni.name, ni.flat_epoch, ni.spec_version)
                                for ni in nodes_list)

    # -- finish-lite: reassemble the previous SnapshotArrays ----------------
    arr = evn["arr"]
    N = evn["N"]
    arr.vocab = vocab
    arr.tasks_list = list(tasks_in_order)
    arr.nodes_list = nodes_list
    arr.jobs_list = jobs_seq
    sigs = asm["sigs"]
    sig_tasks = asm["sig_tasks"]
    if sig_rebuild:
        S = max(len(sigs), 1)
        arr.sig_masks = np.zeros((S, N), dtype=bool)
        if not sig_tasks:
            arr.sig_masks[:, :] = True
    if sig_rebuild or patched_nodes or spec_stale:
        acct_key = (node_key[0].tobytes(), node_key[1].tobytes())
        _fill_sig_masks(cache, arr.sig_masks, sigs, sig_tasks, nodes_list,
                        cache._spec_key, acct_key, N)
    # queue tables: weight/capability re-read from the session's queue
    # objects every cycle (they are cheap and arrive as fresh clones);
    # allocated/request re-zeroed because the allocate action overwrites
    # them in place from the proportion plugin's attrs
    queue_names = asm["queue_names"]
    Q = bucket(max(len(queue_names), 1))
    qw, qc, qa, qr = evn["queue_bufs"]
    if qw.shape[0] != Q:
        qw = np.zeros(Q, dtype=np.float32)
        qc = np.full((Q, R), np.inf, dtype=np.float32)
        qa = np.zeros((Q, R), dtype=np.float32)
        qr = np.zeros((Q, R), dtype=np.float32)
        evn["queue_bufs"] = (qw, qc, qa, qr)
    else:
        qw[:] = 0.0
        qc[:] = np.inf
        qa[:] = 0.0
        qr[:] = 0.0
    qw[:len(queue_names)] = 1.0
    arr.queues_list = queue_names
    arr.queue_weight = qw
    arr.queue_capability = qc
    arr.queue_allocated = qa
    arr.queue_request = qr
    _apply_queue_overrides(arr, asm["queue_index"], queues, vocab)
    # DRF inputs: re-zeroed persistent buffers (the allocate action fills
    # them in place when drf is active); hdrf arrays are rebuilt by
    # build_hdrf per session, so reset to the fresh-flatten default
    da, dt, dp = evn["drf_alloc"], evn["drf_total"], evn["drf_prerank"]
    da[:] = 0.0
    dt[:] = 0.0
    dp[:] = 0
    arr.job_drf_allocated = da
    arr.drf_total = dt
    arr.job_drf_prerank = dp
    arr.hdrf_parent = arr.hdrf_weight = arr.hdrf_depth = None
    arr.hdrf_is_leaf = arr.hdrf_leaf_req = arr.hdrf_job_leaf = None
    arr.hdrf_ancestors = arr.hdrf_total_allocated = None
    arr.thresholds = vocab.thresholds()
    # scalar_dim_mask depends only on R, which is unchanged here
    cache._ev_commit(taken, "event", None, rows_patched)
    return arr, None


def _bulk_node_rows(cache, fast, buf, R: int) -> None:
    """Vectorized node-row recompute for scalar-free nodes: identical
    results (and cache entries) to FlattenCache.node_row, built as four
    [k,2] extractions instead of ~8 to_vector calls per node. The cached
    per-node entries view rows of the bulk arrays (standalone — NOT the
    session buffer, which is rewritten in place next flatten)."""
    k = len(fast)
    idle = np.zeros((k, R), np.float32)
    used = np.zeros((k, R), np.float32)
    extra = np.zeros((k, R), np.float32)
    alloc = np.zeros((k, R), np.float32)
    idle[:, :2] = np.array(
        [(ni.idle.milli_cpu, ni.idle.memory) for _, ni in fast],
        np.float32).reshape(k, 2)
    used[:, :2] = np.array(
        [(ni.used.milli_cpu, ni.used.memory) for _, ni in fast],
        np.float32).reshape(k, 2)
    # subtract in float32 like node_row's to_vector()-to_vector() (a
    # float64 intermediate here would round differently by an ulp and
    # break cold-vs-warm flatten identity)
    rel = np.array([(ni.releasing.milli_cpu, ni.releasing.memory)
                    for _, ni in fast], np.float32).reshape(k, 2)
    pip = np.array([(ni.pipelined.milli_cpu, ni.pipelined.memory)
                    for _, ni in fast], np.float32).reshape(k, 2)
    extra[:, :2] = rel - pip
    alloc[:, :2] = np.array(
        [(ni.allocatable.milli_cpu, ni.allocatable.memory)
         for _, ni in fast], np.float32).reshape(k, 2)
    alloc = np.where(alloc > 0, alloc, 1.0).astype(np.float32)
    npods = np.fromiter(
        (sum(1 for t in ni.tasks.values()
             if t.status != TaskStatus.PIPELINED) for _, ni in fast),
        np.int32, count=k)
    maxp = np.fromiter(
        (ni.allocatable.max_task_num or 1 << 30 for _, ni in fast),
        np.int64, count=k).astype(np.int32, copy=False)
    idxs = np.fromiter((i for i, _ in fast), np.int64, count=k)
    buf["idle"][idxs] = idle
    buf["extra"][idxs] = extra
    buf["used"][idxs] = used
    buf["alloc"][idxs] = alloc
    buf["npods"][idxs] = npods
    buf["maxp"][idxs] = maxp
    rows = cache.node_rows
    for j, (_, ni) in enumerate(fast):
        rows[ni.name] = {
            "v": ni.flat_version, "e": ni.flat_epoch, "R": R,
            "sv": ni.spec_version,
            "idle": idle[j], "used": used[j], "extra": extra[j],
            "alloc": alloc[j], "npods": int(npods[j]),
            "maxp": int(maxp[j])}


def _finish(arr, cache, nodes_list, n_nodes, R, N, node_key, dirty,
            sigs, sig_tasks, queue_index, queue_names, queues):
    vocab = arr.vocab
    # -- node side: persistent buffer, rewrite only changed rows ------------
    # node_key and the dirty positions were computed by flatten_snapshot's
    # single pre-pass over the node list (dirty is None when the previous
    # layout doesn't line up, i.e. every row is dirty)
    buf = cache._node_buf
    reusable = (buf is not None and buf["R"] == R and buf["N"] == N
                and dirty is not None)

    # spec-keyed signature tuple: rebuilt only when a changed node's spec
    # actually moved (name/epoch replacement or a spec_version bump) —
    # pure accounting churn reuses the cached tuple
    sk = cache._spec_key
    spec_stale = not reusable or sk is None or len(sk) != n_nodes
    if not spec_stale:
        # a dirty position whose epoch moved is a replaced node; one whose
        # spec_version moved is a respec'd node — either forces a rebuild,
        # pure accounting bumps (flat_version only) do not
        old_epochs = cache._node_key[0]
        rows = cache.node_rows
        for i in dirty:
            ni = nodes_list[i]
            if node_key[0][i] != old_epochs[i]:
                spec_stale = True
                break
            ent = rows.get(ni.name)
            if ent is None or ent["sv"] != ni.spec_version:
                spec_stale = True
                break
    if spec_stale:
        sk = tuple((ni.name, ni.flat_epoch, ni.spec_version)
                   for ni in nodes_list)
        cache._spec_key = sk

    if not reusable:
        buf = {
            "R": R, "N": N,
            "idle": np.zeros((N, R), dtype=np.float32),
            "extra": np.zeros((N, R), dtype=np.float32),
            "used": np.zeros((N, R), dtype=np.float32),
            "alloc": np.ones((N, R), dtype=np.float32),  # pads: avoid div 0
            "npods": np.zeros(N, dtype=np.int32),
            "maxp": np.zeros(N, dtype=np.int32),
            "valid": np.zeros(N, dtype=bool),
        }
        buf["valid"][:n_nodes] = True
        pending = list(enumerate(nodes_list))
    else:
        pending = [(i, nodes_list[i]) for i in dirty]
    # cold-path vectorization (first cycle / full reship): scalar-free
    # nodes bulk-extract cpu+mem via one list comprehension per column
    # and land in the buffer as fancy-indexed scatters — the per-node
    # to_vector path costs ~11us/node, most of a 2k-node cold flatten
    if len(pending) >= 64:
        rows = cache.node_rows

        def cached_ok(ni):
            ent = rows.get(ni.name)
            return (ent is not None and ent["v"] == ni.flat_version
                    and ent["e"] == ni.flat_epoch and ent["R"] == R)

        # bulk only the nodes node_row would actually RECOMPUTE: a node
        # whose buffer row is stale but whose cache entry is still valid
        # (bucket change, node removal) is a cheap dict hit below
        fast = [(i, ni) for i, ni in pending
                if not cached_ok(ni)
                and not (ni.idle.scalars or ni.used.scalars
                         or ni.releasing.scalars or ni.pipelined.scalars
                         or ni.allocatable.scalars)]
        if len(fast) >= 64:
            _bulk_node_rows(cache, fast, buf, R)
            done = {i for i, _ in fast}
            pending = [(i, ni) for i, ni in pending if i not in done]
    for i, ni in pending:
        row = cache.node_row(ni)
        buf["idle"][i] = row["idle"]
        buf["extra"][i] = row["extra"]
        buf["used"][i] = row["used"]
        buf["alloc"][i] = row["alloc"]
        buf["npods"][i] = row["npods"]
        buf["maxp"][i] = row["maxp"]
    cache._node_key = node_key
    cache._node_buf = buf
    arr.node_idle = buf["idle"]
    arr.node_extra_future = buf["extra"]
    arr.node_used = buf["used"]
    arr.node_alloc = buf["alloc"]
    arr.node_npods = buf["npods"]
    arr.node_max_pods = buf["maxp"]
    arr.node_valid = buf["valid"]

    # -- predicate signature masks (cached per signature x node epoch) ------
    S = max(len(sigs), 1)
    arr.sig_masks = np.zeros((S, N), dtype=bool)
    if not sig_tasks:
        arr.sig_masks[:, :] = True
    # label/taint-only masks survive resource-accounting churn: they key on
    # spec versions (the cached sk tuple); only port-aware masks key on the
    # full accounting state (epoch/version arrays serialized to bytes so
    # the cached-row compare is a memcmp, not 2k tuple compares)
    acct_key = (node_key[0].tobytes(), node_key[1].tobytes())
    _fill_sig_masks(cache, arr.sig_masks, sigs, sig_tasks, nodes_list,
                    sk, acct_key, N)

    # queues (water-filling inputs; overwritten by the allocate action from
    # the proportion plugin's session-open attrs when proportion is active —
    # those cover allocated/request across ALL jobs, not just pending ones)
    Q = bucket(max(len(queue_names), 1))
    arr.queues_list = queue_names
    arr.queue_weight = np.zeros(Q, dtype=np.float32)  # 0 = padded slot
    arr.queue_weight[:len(queue_names)] = 1.0
    arr.queue_capability = np.full((Q, R), np.inf, dtype=np.float32)
    arr.queue_allocated = np.zeros((Q, R), dtype=np.float32)
    arr.queue_request = np.zeros((Q, R), dtype=np.float32)
    _apply_queue_overrides(arr, queue_index, queues, vocab)

    # DRF ordering inputs default to zeros (drf inactive -> static rank);
    # the allocate action overwrites them from the drf plugin's attrs
    arr.job_drf_allocated = np.zeros((arr.job_min.shape[0], R),
                                     dtype=np.float32)
    arr.drf_total = np.zeros(R, dtype=np.float32)
    arr.job_drf_prerank = np.zeros(arr.job_min.shape[0], dtype=np.int32)

    arr.thresholds = vocab.thresholds()
    arr.scalar_dim_mask = np.zeros(R, dtype=bool)
    arr.scalar_dim_mask[2:] = True

    cache.sweep(arr.jobs_list, nodes_list, sigs)
    return arr
