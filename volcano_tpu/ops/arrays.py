"""Snapshot flattening: ClusterInfo -> padded device arrays.

This is the TPU equivalent of the reference's parallel snapshot clone
(cache.go:693-742): each session the host flattens the cluster into
fixed-shape float32/int32 arrays (padded to compile buckets so XLA reuses
compiled executables across cycles) and ships them to the device in one
transfer. Mapping tables (tasks_list / nodes_list / jobs_list) translate
solver outputs back into TaskInfo/NodeInfo objects for Statement replay.

Predicate masks are precomputed host-side per unique constraint signature
(node selector + affinity + tolerations hash) so the device matrix is a
cheap gather: sig_masks[S, N] with S = number of distinct signatures, which
is tiny in practice even when T is 10k.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import (
    JobInfo, NodeInfo, Resource, ResourceVocab, TaskInfo, TaskStatus,
    MIN_MILLI_SCALAR,
)

#: compile-bucket sizes: quarter-steps between powers of two, floor 8 —
#: keeps the number of distinct compiled shapes logarithmic in cluster size
#: while capping padding overhead at 25%
def bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        for frac in (1.25, 1.5, 1.75, 2.0):
            cand = int(b * frac)
            if cand >= n:
                return cand
        b *= 2
    return b


def _match_node_selector(selector: Dict[str, str], node) -> bool:
    labels = node.labels or {}
    return all(labels.get(k) == v for k, v in selector.items())


def _tolerates(tolerations: List[dict], node) -> bool:
    """NoSchedule/NoExecute taints must be tolerated (predicates plugin)."""
    for taint in node.taints or []:
        if taint.get("effect") not in ("NoSchedule", "NoExecute"):
            continue
        tolerated = False
        for tol in tolerations or []:
            op = tol.get("operator", "Equal")
            if tol.get("key") and tol["key"] != taint.get("key"):
                continue
            if op == "Equal" and tol.get("value") != taint.get("value"):
                continue
            if tol.get("effect") and tol["effect"] != taint.get("effect"):
                continue
            tolerated = True
            break
        if not tolerated:
            return False
    return True


def _node_affinity_match(affinity: Optional[dict], node) -> bool:
    """requiredDuringSchedulingIgnoredDuringExecution node affinity subset:
    matchExpressions with In/NotIn/Exists/DoesNotExist operators."""
    if not affinity:
        return True
    na = affinity.get("nodeAffinity") or {}
    req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not req:
        return True
    labels = node.labels or {}
    for term in req.get("nodeSelectorTerms", []):
        ok = True
        for expr in term.get("matchExpressions", []):
            key, op = expr.get("key"), expr.get("operator")
            vals = expr.get("values", [])
            has = key in labels
            if op == "In":
                ok &= has and labels[key] in vals
            elif op == "NotIn":
                ok &= not (has and labels[key] in vals)
            elif op == "Exists":
                ok &= has
            elif op == "DoesNotExist":
                ok &= not has
            if not ok:
                break
        if ok:
            return True  # terms are ORed
    return False


def _signature(task: TaskInfo) -> str:
    pod = task.pod
    if not pod.node_selector and pod.affinity is None and not pod.tolerations:
        ports = pod.ports()
        if not ports:
            return ""  # unconstrained fast path (the common case)
        return json.dumps({"ports": sorted(ports)})
    return json.dumps({
        "sel": sorted((pod.node_selector or {}).items()),
        "aff": pod.affinity,
        "tol": pod.tolerations,
        "ports": sorted(pod.ports()),
    }, sort_keys=True, default=str)


@dataclass
class ScoreParams:
    """Scalar weights feeding the on-device scoring families. Plugins set
    these during OnSessionOpen (binpack/nodeorder register here instead of
    per-(task,node) Python callbacks)."""

    binpack_weight: float = 0.0
    binpack_res_weights: Optional[np.ndarray] = None  # [R]
    least_req_weight: float = 0.0
    most_req_weight: float = 0.0
    balanced_weight: float = 0.0
    # static per-node score added for every task (e.g. node-affinity
    # preferences evaluated host-side): [N]
    node_static: Optional[np.ndarray] = None

    def resolved(self, R: int, N: int) -> "ScoreParams":
        p = ScoreParams(
            binpack_weight=self.binpack_weight,
            least_req_weight=self.least_req_weight,
            most_req_weight=self.most_req_weight,
            balanced_weight=self.balanced_weight)
        p.binpack_res_weights = (
            np.ones(R, dtype=np.float32) if self.binpack_res_weights is None
            else np.asarray(self.binpack_res_weights, dtype=np.float32))
        p.node_static = (
            np.zeros(N, dtype=np.float32) if self.node_static is None
            else np.asarray(self.node_static, dtype=np.float32))
        return p


@dataclass
class SnapshotArrays:
    """Padded array view of one session's decision problem."""

    vocab: ResourceVocab
    # -- tasks (pending tasks of schedulable jobs, in scheduling order) -----
    tasks_list: List[TaskInfo] = field(default_factory=list)
    task_init_req: np.ndarray = None    # [T,R] launch request (fit check)
    task_req: np.ndarray = None         # [T,R] running request (accounting)
    task_job: np.ndarray = None         # [T] -> job index
    task_rank: np.ndarray = None        # [T] global priority order (0 first)
    task_sig: np.ndarray = None         # [T] -> signature index
    task_counts_ready: np.ndarray = None  # [T] bool: counts toward gang
    task_valid: np.ndarray = None       # [T] bool
    # -- jobs ----------------------------------------------------------------
    jobs_list: List[JobInfo] = field(default_factory=list)
    job_min: np.ndarray = None          # [J]
    job_ready_base: np.ndarray = None   # [J] ready_task_num at snapshot
    job_queue: np.ndarray = None        # [J] -> queue index
    job_valid: np.ndarray = None        # [J] bool
    # -- nodes ---------------------------------------------------------------
    nodes_list: List[NodeInfo] = field(default_factory=list)
    node_idle: np.ndarray = None        # [N,R]
    node_extra_future: np.ndarray = None  # [N,R] releasing - pipelined
    node_used: np.ndarray = None        # [N,R]
    node_alloc: np.ndarray = None       # [N,R] allocatable
    node_npods: np.ndarray = None       # [N]
    node_max_pods: np.ndarray = None    # [N]
    node_valid: np.ndarray = None       # [N] bool
    # -- predicate signatures ------------------------------------------------
    sig_masks: np.ndarray = None        # [S,N] bool
    # -- queues --------------------------------------------------------------
    queues_list: List[str] = field(default_factory=list)
    queue_weight: np.ndarray = None     # [Q]
    queue_capability: np.ndarray = None  # [Q,R] (inf where uncapped)
    queue_allocated: np.ndarray = None  # [Q,R]
    # -- misc ----------------------------------------------------------------
    thresholds: np.ndarray = None       # [R]
    scalar_dim_mask: np.ndarray = None  # [R] bool: dims 2+ (ignorable)

    @property
    def T(self) -> int:
        return self.task_init_req.shape[0]

    @property
    def N(self) -> int:
        return self.node_idle.shape[0]

    @property
    def R(self) -> int:
        return self.task_init_req.shape[1]

    @property
    def J(self) -> int:
        return self.job_min.shape[0]

    def packed(self):
        """Pack the solver arrays into one f32 buffer + one i32 buffer so the
        per-session host->device transfer is two puts instead of ~20 (the
        per-transfer latency through the device tunnel dominates at small
        sizes). Returns (fbuf, ibuf, layout); feed to solve_allocate_packed.
        """
        d = self.device_dict()
        fparts, iparts, layout = [], [], []
        foff = ioff = 0
        for k in sorted(d):
            v = d[k]
            if v.dtype == np.float32:
                fparts.append(v.ravel())
                layout.append((k, "f", foff, v.size, v.shape))
                foff += v.size
            elif v.dtype == np.bool_:
                iparts.append(v.ravel().astype(np.int32))
                layout.append((k, "b", ioff, v.size, v.shape))
                ioff += v.size
            else:
                iparts.append(v.ravel().astype(np.int32))
                layout.append((k, "i", ioff, v.size, v.shape))
                ioff += v.size
        fbuf = np.concatenate(fparts) if fparts else np.zeros(0, np.float32)
        ibuf = np.concatenate(iparts) if iparts else np.zeros(0, np.int32)
        return fbuf, ibuf, tuple(layout)

    def device_dict(self) -> Dict[str, np.ndarray]:
        """The arrays the solver kernel consumes (one host->device hop)."""
        return {
            "task_init_req": self.task_init_req,
            "task_req": self.task_req,
            "task_job": self.task_job,
            "task_rank": self.task_rank,
            "task_sig": self.task_sig,
            "task_counts_ready": self.task_counts_ready,
            "task_valid": self.task_valid,
            "job_min": self.job_min,
            "job_ready_base": self.job_ready_base,
            "job_queue": self.job_queue,
            "job_valid": self.job_valid,
            "node_idle": self.node_idle,
            "node_extra_future": self.node_extra_future,
            "node_used": self.node_used,
            "node_alloc": self.node_alloc,
            "node_npods": self.node_npods,
            "node_max_pods": self.node_max_pods,
            "node_valid": self.node_valid,
            "sig_masks": self.sig_masks,
            "thresholds": self.thresholds,
            "scalar_dim_mask": self.scalar_dim_mask,
        }


def flatten_snapshot(
    jobs: Dict[str, JobInfo],
    nodes: Dict[str, NodeInfo],
    tasks_in_order: List[TaskInfo],
    vocab: Optional[ResourceVocab] = None,
    queues: Optional[Dict[str, object]] = None,
) -> SnapshotArrays:
    """Flatten session state into padded arrays.

    tasks_in_order: the pending tasks to place, already sorted by the
    session's namespace/queue/job/task ordering (host-side comparator pass —
    the ordering semantics stay in Python, the math goes on device).
    Tasks must be grouped by job within the order.
    """
    if vocab is None:
        resources = []
        for ni in nodes.values():
            resources.append(ni.allocatable)
        for t in tasks_in_order:
            resources.append(t.init_resreq)
        vocab = ResourceVocab.collect(resources)

    R = len(vocab)
    nodes_list = [n for n in nodes.values() if n.ready]
    N = bucket(max(len(nodes_list), 1))
    T = bucket(max(len(tasks_in_order), 1))

    job_keys: List[str] = []
    job_index: Dict[str, int] = {}
    for t in tasks_in_order:
        if t.job not in job_index:
            job_index[t.job] = len(job_keys)
            job_keys.append(t.job)
    # +1 guarantees a padded (invalid) job slot: padded tasks point there so
    # the sequential solver's job-boundary logic never revisits a real job
    J = bucket(len(job_keys) + 1)

    arr = SnapshotArrays(vocab=vocab)
    arr.tasks_list = list(tasks_in_order)
    arr.nodes_list = nodes_list
    arr.jobs_list = [jobs[k] for k in job_keys]

    arr.task_init_req = np.zeros((T, R), dtype=np.float32)
    arr.task_req = np.zeros((T, R), dtype=np.float32)
    arr.task_job = np.full(T, J - 1, dtype=np.int32)  # padded job slot
    arr.task_rank = np.arange(T, dtype=np.int32)
    arr.task_sig = np.zeros(T, dtype=np.int32)
    arr.task_counts_ready = np.zeros(T, dtype=bool)
    arr.task_valid = np.zeros(T, dtype=bool)

    n_tasks = len(tasks_in_order)
    if n_tasks:
        # bulk columns (vectorized: the per-session flatten is on the
        # critical path of every cycle)
        arr.task_init_req[:n_tasks, 0] = np.fromiter(
            (t.init_resreq.milli_cpu for t in tasks_in_order), np.float32,
            n_tasks)
        arr.task_init_req[:n_tasks, 1] = np.fromiter(
            (t.init_resreq.memory for t in tasks_in_order), np.float32,
            n_tasks)
        arr.task_req[:n_tasks, 0] = np.fromiter(
            (t.resreq.milli_cpu for t in tasks_in_order), np.float32, n_tasks)
        arr.task_req[:n_tasks, 1] = np.fromiter(
            (t.resreq.memory for t in tasks_in_order), np.float32, n_tasks)
        arr.task_job[:n_tasks] = np.fromiter(
            (job_index[t.job] for t in tasks_in_order), np.int32, n_tasks)
        arr.task_valid[:n_tasks] = True
    sigs: Dict[str, int] = {}
    sig_tasks: List[TaskInfo] = []
    for i, t in enumerate(tasks_in_order):
        for name, v in t.init_resreq.scalars.items():
            idx = vocab.index(name)
            if idx is not None:
                arr.task_init_req[i, idx] = v
        for name, v in t.resreq.scalars.items():
            idx = vocab.index(name)
            if idx is not None:
                arr.task_req[i, idx] = v
        s = _signature(t)
        if s not in sigs:
            sigs[s] = len(sigs)
            sig_tasks.append(t)
        arr.task_sig[i] = sigs[s]
        # best-effort pending tasks already count in ready_task_num
        arr.task_counts_ready[i] = not t.init_resreq.is_empty()

    arr.job_min = np.zeros(J, dtype=np.int32)
    arr.job_ready_base = np.zeros(J, dtype=np.int32)
    arr.job_queue = np.zeros(J, dtype=np.int32)
    arr.job_valid = np.zeros(J, dtype=bool)
    queue_index: Dict[str, int] = {}
    queue_names: List[str] = []
    for j, key in enumerate(job_keys):
        job = jobs[key]
        arr.job_min[j] = job.min_available
        arr.job_ready_base[j] = job.ready_task_num()
        arr.job_valid[j] = True
        if job.queue not in queue_index:
            queue_index[job.queue] = len(queue_names)
            queue_names.append(job.queue)
        arr.job_queue[j] = queue_index[job.queue]

    arr.node_idle = np.zeros((N, R), dtype=np.float32)
    arr.node_extra_future = np.zeros((N, R), dtype=np.float32)
    arr.node_used = np.zeros((N, R), dtype=np.float32)
    arr.node_alloc = np.ones((N, R), dtype=np.float32)  # avoid div by 0 in pads
    arr.node_npods = np.zeros(N, dtype=np.int32)
    arr.node_max_pods = np.zeros(N, dtype=np.int32)
    arr.node_valid = np.zeros(N, dtype=bool)
    n_nodes = len(nodes_list)
    if n_nodes:
        for col, attr in ((arr.node_idle, "idle"), (arr.node_used, "used")):
            col[:n_nodes, 0] = np.fromiter(
                (getattr(n, attr).milli_cpu for n in nodes_list), np.float32,
                n_nodes)
            col[:n_nodes, 1] = np.fromiter(
                (getattr(n, attr).memory for n in nodes_list), np.float32,
                n_nodes)
        arr.node_extra_future[:n_nodes, 0] = np.fromiter(
            (n.releasing.milli_cpu - n.pipelined.milli_cpu
             for n in nodes_list), np.float32, n_nodes)
        arr.node_extra_future[:n_nodes, 1] = np.fromiter(
            (n.releasing.memory - n.pipelined.memory for n in nodes_list),
            np.float32, n_nodes)
        alloc_cpu = np.fromiter(
            (n.allocatable.milli_cpu for n in nodes_list), np.float32, n_nodes)
        alloc_mem = np.fromiter(
            (n.allocatable.memory for n in nodes_list), np.float32, n_nodes)
        arr.node_alloc[:n_nodes, 0] = np.where(alloc_cpu > 0, alloc_cpu, 1.0)
        arr.node_alloc[:n_nodes, 1] = np.where(alloc_mem > 0, alloc_mem, 1.0)
        arr.node_npods[:n_nodes] = np.fromiter(
            (sum(1 for t in n.tasks.values()
                 if t.status != TaskStatus.PIPELINED) for n in nodes_list),
            np.int32, n_nodes)
        arr.node_max_pods[:n_nodes] = np.fromiter(
            (n.allocatable.max_task_num or 1 << 30 for n in nodes_list),
            np.int32, n_nodes)
        arr.node_valid[:n_nodes] = True
        if len(vocab) > 2:
            for i, ni in enumerate(nodes_list):
                for res, col in ((ni.idle, arr.node_idle),
                                 (ni.used, arr.node_used)):
                    for name, v in res.scalars.items():
                        idx = vocab.index(name)
                        if idx is not None:
                            col[i, idx] = v
                for name, v in ni.allocatable.scalars.items():
                    idx = vocab.index(name)
                    if idx is not None and v > 0:
                        arr.node_alloc[i, idx] = v
                for name, v in ni.releasing.scalars.items():
                    idx = vocab.index(name)
                    if idx is not None:
                        arr.node_extra_future[i, idx] += v
                for name, v in ni.pipelined.scalars.items():
                    idx = vocab.index(name)
                    if idx is not None:
                        arr.node_extra_future[i, idx] -= v

    S = max(len(sigs), 1)
    arr.sig_masks = np.zeros((S, N), dtype=bool)
    if not sig_tasks:
        arr.sig_masks[:, :] = True
    for s_idx, t in enumerate(sig_tasks):
        pod = t.pod
        for n_idx, ni in enumerate(nodes_list):
            node = ni.node
            ok = True
            if node is not None:
                ok = (_match_node_selector(pod.node_selector or {}, node)
                      and _tolerates(pod.tolerations, node)
                      and _node_affinity_match(pod.affinity, node))
                if ok and pod.ports():
                    taken = set()
                    for other in ni.tasks.values():
                        taken.update(other.pod.ports())
                    ok = not (set(pod.ports()) & taken)
            arr.sig_masks[s_idx, n_idx] = ok

    # queues (water-filling inputs; filled further by proportion plugin)
    Q = bucket(max(len(queue_names), 1))
    arr.queues_list = queue_names
    arr.queue_weight = np.ones(Q, dtype=np.float32)
    arr.queue_capability = np.full((Q, R), np.inf, dtype=np.float32)
    arr.queue_allocated = np.zeros((Q, R), dtype=np.float32)
    if queues:
        for name, q_idx in queue_index.items():
            qi = queues.get(name)
            if qi is None:
                continue
            arr.queue_weight[q_idx] = getattr(qi, "weight", 1) or 1
            cap = getattr(qi, "capability", None)
            if cap:
                cap_vec = Resource.from_resource_list(cap).to_vector(vocab)
                arr.queue_capability[q_idx] = np.where(
                    cap_vec > 0, cap_vec, np.inf)

    arr.thresholds = vocab.thresholds()
    arr.scalar_dim_mask = np.zeros(R, dtype=bool)
    arr.scalar_dim_mask[2:] = True
    return arr
