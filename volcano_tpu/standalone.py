"""Standalone dev cluster: every component in one process.

The reference deploys three binaries against a Kubernetes API server
(installer/volcano-development.yaml). This module is the TPU build's
single-process equivalent for development and e2e use: one ClusterStore
plays the API server, and around it run

- the admission chain (in-process interceptors + optional TLS server),
- the controller manager (job/queue/podgroup/kubelet-standin/gc),
- the scheduler loop (solver on the local chip or via the solver sidecar),
- the metrics endpoint (/metrics, /healthz, /debug/stacks).

``python -m volcano_tpu.standalone [--conf scheduler.yaml] [--period 1.0]
[--serve-webhooks] [--sidecar /path/to.sock] [--metrics-port 8080]``

Jobs are submitted with the in-process CLI against the same store when
embedding, or by pointing --jobs-dir at a directory of job YAMLs (each
file is applied once; the reference's e2e suites submit via vcctl).
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)


class Standalone:
    def __init__(self, scheduler_conf: Optional[str] = None,
                 period: float = 1.0, serve_webhooks_tls: bool = False,
                 sidecar_path: Optional[str] = None,
                 metrics_port: int = 0,
                 async_effectors: bool = True,
                 serve_store: Optional[str] = None,
                 webhook_client_ca: Optional[str] = None,
                 webhook_bind: Optional[str] = None,
                 store_token: Optional[str] = None,
                 scheduler_name: str = "volcano",
                 default_queue: str = "default",
                 percentage_of_nodes_to_find: int = 100,
                 leader_elect: bool = False,
                 compile_cache_dir: Optional[str] = None,
                 prewarm: bool = False,
                 pipeline_solver: bool = True,
                 pipeline_effects: bool = False,
                 action_deadline_s: Optional[float] = None,
                 breaker_failures: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 sim_record: Optional[str] = None,
                 sim_trace: Optional[str] = None,
                 solver_mode: Optional[str] = None,
                 sharded_byte_budget: int = 0,
                 reschedule_interval: int = 0,
                 reschedule_max_moves: Optional[int] = None,
                 reschedule_max_disruption: Optional[int] = None,
                 reschedule_min_improvement: Optional[float] = None,
                 store_data_dir: Optional[str] = None,
                 store_fsync: str = "every",
                 store_fsync_interval_s: float = 0.05,
                 store_snapshot_every: int = 4096,
                 store_shards: int = 1,
                 store_shard_procs: bool = False,
                 controller_shard_workers: int = 1,
                 admission_lanes: Optional[str] = None,
                 admission_queue_wait_ms: Optional[float] = None,
                 controllers_read_endpoint: Optional[str] = None):
        from .cache import SchedulerCache
        from .client import ClusterStore
        from .controllers import ControllerManager
        from .metrics.server import MetricsServer
        from .scheduler import Scheduler
        from .webhooks import start_webhooks

        # overload-protected front door (resilience/overload.py): every
        # served endpoint gets an admission gate — fail-safe defaults
        # (gate on, generous limits) unless --admission-lanes tightens
        # them; shard WORKERS each get their own gate via the supervisor
        from .resilience.overload import AdmissionGate, parse_lane_spec

        def make_gate():
            kw = {}
            if admission_queue_wait_ms is not None:
                kw["queue_wait_ms"] = admission_queue_wait_ms
            return AdmissionGate(parse_lane_spec(admission_lanes), **kw)

        self._shard_supervisor = None
        if store_shard_procs:
            # every shard in its OWN OS process (the multi-process
            # front door, client/shardproc.py): workers own their
            # lock/rv/journal/WAL lineages AND run the admission chain
            # at the authoritative store; a thin ProcShardRouter in
            # this process supervises them and serves one endpoint,
            # and this process's own consumers (cache, controllers,
            # scheduler) ride a direct-routing RemoteClusterStore —
            # single-key traffic bypasses the router like any other
            # client's.
            from .client import (
                ProcShardRouter, ProcShardedStore, RemoteClusterStore,
                ShardProcSupervisor,
            )
            host, port = "127.0.0.1", 0
            if serve_store:
                h, _, p = serve_store.rpartition(":")
                host, port = (h or "127.0.0.1"), int(p)
            token = store_token if store_token is not None \
                else os.environ.get("VOLCANO_STORE_TOKEN", "")
            if not token and host not in ("127.0.0.1", "localhost",
                                          "::1"):
                raise ValueError(
                    f"--serve-store on non-loopback {host!r} requires "
                    "a shared token (set VOLCANO_STORE_TOKEN)")
            self._shard_supervisor = ShardProcSupervisor(
                max(1, store_shards),
                data_dir=store_data_dir or None,
                fsync=store_fsync,
                fsync_interval_s=store_fsync_interval_s,
                snapshot_every=store_snapshot_every,
                token=token or None,
                scheduler_name=scheduler_name,
                default_queue=default_queue,
                admission_lanes=admission_lanes,
                admission_queue_wait_ms=admission_queue_wait_ms).start()
            self.store_server = ProcShardRouter(
                ProcShardedStore(self._shard_supervisor),
                host, port, token=token or None,
                gate=make_gate()).start()
            self.store = RemoteClusterStore(
                self.store_server.address, token=token or None,
                direct_watch=True)
        elif store_shards > 1:
            # the partitioned front door (ROADMAP item 3): N member
            # stores behind deterministic (kind, namespace/name) hash
            # routing, each with its own lock, resume journal and —
            # with --store-data-dir — its own WAL+snapshot lineage under
            # data_dir/shard-NNN (each shard recovers from only its own
            # WAL). shards=1 keeps the exact historical code paths.
            from .client import ShardedClusterStore
            self.store = ShardedClusterStore(
                store_shards, data_dir=store_data_dir or None,
                fsync=store_fsync,
                fsync_interval_s=store_fsync_interval_s,
                snapshot_every=store_snapshot_every)
        elif store_data_dir:
            # durable control plane: WAL + snapshots under the data dir,
            # recovery (snapshot load + WAL replay) happens right here in
            # the constructor — jobs, leases and both intent journals
            # survive a store crash. The in-memory default stays untouched.
            from .client import DurableClusterStore
            self.store = DurableClusterStore(
                store_data_dir, fsync=store_fsync,
                fsync_interval_s=store_fsync_interval_s,
                snapshot_every=store_snapshot_every)
        else:
            self.store = ClusterStore()
        # admission interceptors must be installed BEFORE the store starts
        # accepting remote writes, or an early vcctl create slips past the
        # webhook chain (recovery above bypasses admission by design: the
        # recovered objects were admitted when they first committed).
        # With --store-shard-procs the chain already runs INSIDE each
        # worker process (the authoritative store); this process is just
        # another client and must not (and cannot) install interceptors.
        if self._shard_supervisor is None:
            start_webhooks(self.store, scheduler_name=scheduler_name,
                           default_queue=default_queue)
        else:
            serve_store = None  # the ProcShardRouter above IS the server
        if self._shard_supervisor is None:
            self.store_server = None
        if serve_store:
            # the API-server seam as an actual server: vcctl --server and
            # remote scheduler caches drive this store over TCP
            from .client import StoreServer
            host, _, port = serve_store.rpartition(":")
            host = host or "127.0.0.1"
            token = store_token if store_token is not None \
                else os.environ.get("VOLCANO_STORE_TOKEN", "")
            if not token and host not in ("127.0.0.1", "localhost", "::1"):
                # the store holds Secrets and the HA lease; exposing it
                # unauthenticated beyond loopback hands cluster control
                # to anything that can reach the port
                raise ValueError(
                    f"--serve-store on non-loopback {host!r} requires a "
                    "shared token (set VOLCANO_STORE_TOKEN)")
            tls_cert = os.environ.get("VOLCANO_STORE_TLS_CERT") or None
            tls_key = os.environ.get("VOLCANO_STORE_TLS_KEY") or None
            tls_ca = os.environ.get("VOLCANO_STORE_CLIENT_CA") or None
            if (tls_cert is None) != (tls_key is None):
                raise ValueError(
                    "VOLCANO_STORE_TLS_CERT and VOLCANO_STORE_TLS_KEY "
                    "must be set together")
            if not (tls_cert and tls_key) and host not in (
                    "127.0.0.1", "localhost", "::1"):
                # plaintext beyond loopback leaks the token and every
                # Secret to the network path; allow it only when the
                # operator explicitly claims link-layer encryption
                if os.environ.get(
                        "VOLCANO_STORE_ALLOW_PLAINTEXT") != "1":
                    raise ValueError(
                        f"--serve-store on non-loopback {host!r} without "
                        "TLS (set VOLCANO_STORE_TLS_CERT/"
                        "VOLCANO_STORE_TLS_KEY, or acknowledge an "
                        "encrypted network layer with "
                        "VOLCANO_STORE_ALLOW_PLAINTEXT=1)")
            server_cls = StoreServer
            if store_shards > 1:
                # same wire protocol, one endpoint, N shards behind it
                from .client import ShardRouter
                server_cls = ShardRouter
            self.store_server = server_cls(
                self.store, host, int(port), token=token,
                tls_cert=tls_cert, tls_key=tls_key,
                tls_client_ca=tls_ca, gate=make_gate()).start()
        self.webhook_server = None
        if serve_webhooks_tls:
            from .webhooks import serve_webhooks
            wh_host, wh_port = "127.0.0.1", 0
            if webhook_bind:
                h, _, p = webhook_bind.rpartition(":")
                wh_host, wh_port = (h or "127.0.0.1"), int(p)
            if wh_host not in ("127.0.0.1", "localhost", "::1") \
                    and not webhook_client_ca:
                # same fail-closed rule as the store port: an admission
                # endpoint reachable beyond loopback must authenticate
                # its clients
                raise ValueError(
                    f"--webhook-bind on non-loopback {wh_host!r} requires "
                    "--webhook-client-ca (mutual TLS)")
            self.webhook_server = serve_webhooks(
                self.store, host=wh_host, port=wh_port,
                client_ca_path=webhook_client_ca,
                scheduler_name=scheduler_name,
                default_queue=default_queue)
            self.webhook_server.start_background()
        self.cache = SchedulerCache(self.store,
                                    scheduler_name=scheduler_name,
                                    async_effectors=async_effectors)
        # --sim-record: attach the sim's decision recorder to the LIVE
        # control plane — every cycle's binds/evicts/pipelines/FitErrors
        # append to the JSONL trace (non-strict: live traces timestamp
        # with wall time; reproducibility is the virtual-clock sim's job)
        self._turn = 0
        self.sim_recorder = None
        self._sim_record_file = None
        if sim_record:
            from .cache import RecordingBinder, RecordingEvictor
            from .sim.recorder import DecisionRecorder
            self._sim_record_file = open(sim_record, "a")
            rec = DecisionRecorder(clock=lambda: time.time(),
                                   sink=self._sim_record_file,
                                   strict=False)
            self.sim_recorder = rec
            self.cache.decision_recorder = rec
            self.cache.binder = RecordingBinder(
                self.cache.binder,
                on_bind=lambda pod, h: rec.record_bind(
                    f"{pod.namespace}/{pod.name}", h))
            self.cache.evictor = RecordingEvictor(
                self.cache.evictor,
                on_evict=lambda pod, r: rec.record_evict(
                    f"{pod.namespace}/{pod.name}", r))
        # --sim-trace: drive this control plane from a recorded workload
        # trace (sim/workload.py JSONL) — each control-plane turn submits
        # the events whose arrival cycle has come due
        self._sim_events = []
        if sim_trace:
            from .sim.workload import Workload
            wl = Workload.load(sim_trace)
            self._sim_events = sorted(wl.events, key=lambda e: int(e["t"]))
            # the trace's queues/priority classes must exist before its
            # jobs are admitted (the jobs webhook rejects unknown queues),
            # and the header's node pool is materialized so the trace is
            # actually runnable — in standalone the ClusterStore IS the
            # cluster, there are no real kubelets to register nodes
            self.store.bulk_apply(
                [("queues", q) for q in wl.queue_objects()]
                + [("priorityclasses", pc)
                   for pc in wl.priority_class_objects()]
                + [("nodes", node) for node in wl.node_objects()
                   if self.store.try_get("nodes", node.name) is None])
        if sidecar_path:
            from .parallel.sidecar import SidecarSolver
            self.cache.sidecar = SidecarSolver(sidecar_path)
        self.cache.run()
        # controller traffic rides the CONTROL admission lane: when the
        # store is a remote client (shard-procs mode) the LaneStore view
        # tags every controller op so the gate can shed read storms
        # without starving the control plane's own feedback loops
        ctrl_store = self.store
        if self._shard_supervisor is not None:
            from .resilience.overload import LaneStore
            ctrl_store = LaneStore(self.store, "control")
        # --controllers-read-endpoint: serve the controllers' steady-
        # state reads (list/watch/bulk_watch) from a replica endpoint
        # while their mutations keep flowing here (ROADMAP item 1);
        # read-your-writes holds via the min_rv bound (client/readtier)
        self._controllers_read_client = None
        ctrl_read = None
        if controllers_read_endpoint:
            from .client import RemoteClusterStore
            self._controllers_read_client = RemoteClusterStore(
                controllers_read_endpoint,
                token=store_token if store_token is not None
                else os.environ.get("VOLCANO_STORE_TOKEN", ""),
                direct_routing=False)
            ctrl_read = self._controllers_read_client
        self.controllers = ControllerManager(
            ctrl_store, scheduler_name=scheduler_name,
            default_queue=default_queue,
            shard_workers=controller_shard_workers,
            read_store=ctrl_read)
        self.controllers.run()
        self.scheduler = Scheduler(
            self.cache, scheduler_conf=scheduler_conf, period=period,
            percentage_of_nodes_to_find=percentage_of_nodes_to_find,
            compile_cache_dir=compile_cache_dir, prewarm=prewarm,
            pipeline_solver=pipeline_solver,
            action_deadline_s=action_deadline_s,
            breaker_failures=breaker_failures,
            breaker_cooldown_s=breaker_cooldown_s,
            solver_mode=solver_mode,
            sharded_byte_budget=sharded_byte_budget,
            reschedule_interval=reschedule_interval,
            reschedule_max_moves=reschedule_max_moves,
            reschedule_max_disruption=reschedule_max_disruption,
            reschedule_min_improvement=reschedule_min_improvement)
        # pipeline_effects: don't drain the async bind effectors between
        # control-plane turns — cycle N's API writes overlap cycle N+1's
        # snapshot+flatten (see Scheduler.run). Off by default: embedding
        # tests want each run_once() deterministic and fully applied.
        self.pipeline_effects = pipeline_effects
        self.leader_elect = leader_elect
        self._elector = None
        self.metrics_server = MetricsServer(port=metrics_port).start()
        self._stop = threading.Event()

    def run_once(self, drain_effects: bool = True) -> None:
        """One control-plane turn: controllers drain, scheduler cycles.
        ``drain_effects=False`` (the run() loop under pipeline_effects)
        leaves async binds in flight so they overlap the next turn."""
        while self._sim_events and int(self._sim_events[0]["t"]) \
                <= self._turn:
            # --sim-trace arrivals due this turn, submitted as Jobs so
            # they take the full admission + job-controller path
            from .sim.workload import build_job_crd
            self.store.create("jobs",
                              build_job_crd(self._sim_events.pop(0)))
        rec = self.sim_recorder
        if rec is not None:
            rec.begin_cycle(self._turn)
        self.controllers.process_all()
        self.scheduler.run_once()
        self.controllers.process_all()
        if drain_effects:
            self.cache.wait_for_effects()
        if rec is not None:
            rec.end_cycle(self.scheduler.last_cycle_timing)
        self._turn += 1

    def run(self) -> None:
        if self.leader_elect:
            # HA mode (cmd/scheduler/app/server.go:85-145): only the
            # lease holder turns the control plane; a standby pointed at
            # the same (remote) store takes over when the lease expires
            from .utils import LeaderElector, LeaseLock

            elector = LeaderElector(LeaseLock(self.store, "volcano"))
            self._elector = elector
            # release is deferred to stop(): the SIGTERM contract hands
            # the lease over only after the async bind effectors drained
            renewer = threading.Thread(target=elector.run,
                                       args=(self._stop,),
                                       kwargs={"release_on_stop": False},
                                       name="leader-elector", daemon=True)
            renewer.start()
        while not self._stop.is_set():
            if self._elector is not None and not self._elector.is_leader:
                self._stop.wait(0.05)
                continue
            t0 = time.time()
            try:
                self.run_once(drain_effects=not self.pipeline_effects)
            except Exception:
                log.exception("control-plane turn failed")
            delay = self.scheduler.period - (time.time() - t0)
            if delay > 0:
                self._stop.wait(delay)

    def stop(self) -> None:
        self._stop.set()
        self.cache.wait_for_effects()  # land in-flight pipelined binds
        if self._elector is not None:
            # release AFTER the drain: a standby taking over mid-drain
            # would race this process's last bind writes
            self._elector.release()
        if self._sim_record_file is not None:
            self._sim_record_file.close()
            self._sim_record_file = None
        self.metrics_server.stop()
        if self.store_server is not None:
            self.store_server.stop()
        if self._shard_supervisor is not None:
            self._shard_supervisor.stop()
        if self.webhook_server is not None:
            self.webhook_server.shutdown()
        if self._controllers_read_client is not None:
            self._controllers_read_client.close()
        close = getattr(self.store, "close", None)
        if close is not None:
            close()  # flush + fsync the WAL (recovery never depends on it)

    def apply_job_yaml(self, text: str) -> None:
        import yaml

        from .cli.vcctl import _job_from_yaml

        self.store.create("jobs", _job_from_yaml(yaml.safe_load(text)))


def run_replica(primary: str, serve: str, metrics_port: int = 0,
                admission_lanes: Optional[str] = None,
                admission_queue_wait_ms: Optional[float] = None) -> int:
    """Replica-only process mode (``--store-replica-of``): no scheduler,
    no controllers, no webhooks — bootstrap from the primary's newest
    snapshot, tail its shipped WAL, and serve the read tier
    (list/get/watch/bulk_watch with explicit rv-bounded staleness;
    mutations fail closed with ReplicaReadOnlyError)."""
    import signal

    from .client import ReplicaStore
    from .metrics.server import MetricsServer

    host, _, port = serve.rpartition(":")
    host = host or "127.0.0.1"
    token = os.environ.get("VOLCANO_STORE_TOKEN", "")
    if not token and host not in ("127.0.0.1", "localhost", "::1"):
        # the replica mirrors Secrets and the HA lease: the same
        # fail-closed exposure rule as --serve-store applies
        raise ValueError(
            f"--serve-replica on non-loopback {host!r} requires a "
            "shared token (set VOLCANO_STORE_TOKEN)")
    tls_cert = os.environ.get("VOLCANO_STORE_TLS_CERT") or None
    tls_key = os.environ.get("VOLCANO_STORE_TLS_KEY") or None
    replica = ReplicaStore(primary, token=token or None,
                           tls_ca=os.environ.get("VOLCANO_STORE_CA")
                           or None)
    # the replica IS the read tier: its gate sheds list/watch storms
    # typed instead of letting them starve the tailer keeping it fresh
    from .resilience.overload import AdmissionGate, parse_lane_spec
    gate_kw = {}
    if admission_queue_wait_ms is not None:
        gate_kw["queue_wait_ms"] = admission_queue_wait_ms
    server = replica.serve(host, int(port), token=token or None,
                           tls_cert=tls_cert, tls_key=tls_key,
                           gate=AdmissionGate(
                               parse_lane_spec(admission_lanes),
                               **gate_kw))
    replica.start()
    metrics_server = MetricsServer(port=metrics_port).start()
    print(f"volcano-tpu replica up; following {primary}; serving reads "
          f"on {server.address}; metrics on :{metrics_server.port}",
          flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    try:
        while not stop.is_set():
            stop.wait(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        metrics_server.stop()
        replica.close()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="volcano-tpu-standalone")
    ap.add_argument("--conf", help="scheduler conf YAML path")
    ap.add_argument("--period", type=float, default=1.0)
    ap.add_argument("--serve-webhooks", action="store_true",
                    help="also serve admission over TLS")
    ap.add_argument("--sidecar", help="solver sidecar socket path")
    ap.add_argument("--metrics-port", type=int, default=8080)
    ap.add_argument("--jobs-dir", help="apply every .yaml job in this dir")
    ap.add_argument("--webhook-client-ca", metavar="CA_PEM",
                    help="require mutual TLS on the admission server: "
                         "only clients presenting a cert signed by this "
                         "CA may drive admission")
    ap.add_argument("--webhook-bind", metavar="[HOST:]PORT",
                    help="admission server bind address (default "
                         "loopback, ephemeral port — a deployment that "
                         "advertises a webhook Service must set this)")
    ap.add_argument("--serve-store", metavar="[HOST:]PORT",
                    help="serve the cluster store over TCP so vcctl "
                         "--server and remote components can drive this "
                         "control plane; non-loopback binds require "
                         "VOLCANO_STORE_TOKEN (shared-secret auth)")
    ap.add_argument("--store-data-dir", metavar="DIR",
                    help="make the cluster store DURABLE: every committed "
                         "mutation appends one fsync'd record to a "
                         "write-ahead log under DIR, compacted into "
                         "snapshots; on start the store recovers (newest "
                         "valid snapshot + WAL tail replay) so jobs, "
                         "leases and the bind/migration intent journals "
                         "survive a store crash. Default: in-memory, "
                         "nothing touches disk")
    ap.add_argument("--store-fsync", default="every",
                    choices=["every", "interval", "off"],
                    help="WAL durability: 'every' fsyncs each commit "
                         "(acked => durable), 'interval' group-commits "
                         "(at most one fsync per --store-fsync-interval; "
                         "a crash can lose the last interval), 'off' "
                         "never fsyncs (survives process kill, not "
                         "power loss)")
    ap.add_argument("--store-fsync-interval", type=float, default=0.05,
                    metavar="SECS",
                    help="group-commit window for --store-fsync interval")
    ap.add_argument("--store-snapshot-every", type=int, default=4096,
                    metavar="N",
                    help="WAL records between snapshot compactions "
                         "(bounds both recovery replay length and "
                         "on-disk log growth)")
    ap.add_argument("--store-shards", type=int, default=1, metavar="N",
                    help="partition the cluster store into N shards "
                         "keyed by (kind, namespace/name) hash, each "
                         "with its own lock, watch-resume journal and "
                         "(with --store-data-dir) its own WAL+snapshot "
                         "lineage; --serve-store then serves all shards "
                         "through one endpoint speaking the unchanged "
                         "wire protocol. Default 1: the exact "
                         "historical single-store code paths")
    ap.add_argument("--store-shard-procs", action="store_true",
                    help="promote each store shard to its OWN OS "
                         "process (break the GIL): shard workers own "
                         "their WAL lineages and run admission; a thin "
                         "router in this process supervises them "
                         "(capped-backoff restart on the same data "
                         "dir), serves one endpoint on --serve-store, "
                         "and publishes the shard map via the "
                         "'topology' op so clients route single-key "
                         "ops straight to the owning worker")
    ap.add_argument("--store-replica-of", metavar="HOST:PORT",
                    dest="store_replica_of",
                    help="run as a READ REPLICA of the durable store at "
                         "HOST:PORT (a --serve-store primary with "
                         "--store-data-dir): bootstrap from its newest "
                         "snapshot, tail its shipped WAL, and serve "
                         "list/watch with explicit rv-bounded staleness "
                         "on --serve-replica. Replica mode runs NO "
                         "scheduler/controllers; mutations against the "
                         "replica fail closed")
    ap.add_argument("--serve-replica", metavar="[HOST:]PORT",
                    dest="serve_replica",
                    help="bind address for the replica read endpoint "
                         "(requires --store-replica-of; same wire "
                         "protocol and auth/TLS rules as --serve-store)")
    ap.add_argument("--admission-lanes", default=None, metavar="SPEC",
                    help="per-lane overload-admission bounds for every "
                         "served store endpoint (and, with "
                         "--store-shard-procs, each worker's own gate): "
                         "lane=inflight[:queue[:streams]] comma-"
                         "separated, 0 = unbounded. Lanes: system "
                         "(fenced writes/leases — never shed), control "
                         "(controller syncs, bulk_watch/resume), bulk "
                         "(bulk_apply waves), read (lists/gets/plain "
                         "watch — sheds first). Default: gate ON with "
                         "generous fail-safe limits "
                         "(control=64:256, bulk=32:128, read=64:1024); "
                         "an unloaded deployment is protocol-"
                         "indistinguishable from an ungated one. "
                         "Example: read=16:64:32,bulk=8:32")
    ap.add_argument("--admission-queue-wait-ms", type=float,
                    default=None, metavar="MS",
                    help="max milliseconds a request waits in a full "
                         "admission lane before it is shed with a "
                         "typed OverloadedError + retry-after hint "
                         "(default 2000; requests carrying a tighter "
                         "wire deadline_ms shed at that instead)")
    ap.add_argument("--controllers-read-endpoint", metavar="HOST:PORT",
                    dest="controllers_read_endpoint",
                    help="serve the controllers' list/watch/bulk_watch "
                         "from the replica at HOST:PORT (any depth in a "
                         "fan-out tree) while their mutations keep "
                         "flowing to this process's store; read-your-"
                         "writes holds via the min_rv bound, and a "
                         "lagging/unreachable replica degrades reads "
                         "back to the primary, typed and counted")
    ap.add_argument("--controller-shard-workers", type=int, default=1,
                    metavar="N",
                    help="fan the job controller's sync drain out "
                         "across N workers partitioned by store shard "
                         "(key affinity preserved); default 1 = the "
                         "historical serial drain")
    ap.add_argument("--scheduler-name", default="volcano",
                    help="only schedule pods/jobs naming this scheduler "
                         "(options.go: --scheduler-name)")
    ap.add_argument("--default-queue", default="default",
                    help="queue assigned to jobs/podgroups that name "
                         "none (options.go: --default-queue)")
    ap.add_argument("--percentage-nodes-to-find", type=int, default=100,
                    help="adaptive node sampling target percentage "
                         "(options.go: --percentage-nodes-to-find)")
    ap.add_argument("--leader-elect", action="store_true",
                    help="contend on the 'volcano' lease; only the "
                         "holder runs control-plane turns")
    ap.add_argument("--compile-cache-dir", metavar="DIR",
                    help="persistent XLA compilation cache directory "
                         "(default $VOLCANO_COMPILE_CACHE_DIR): restarts "
                         "and repeated bucket shapes skip recompiles")
    ap.add_argument("--prewarm", action="store_true",
                    help="compile the next compile-bucket's solver "
                         "variants on a background thread when occupancy "
                         "nears the current bucket")
    ap.add_argument("--serial-solver", action="store_true",
                    help="disable the allocate dispatch/collect overlap "
                         "(debug/parity; decisions are identical)")
    ap.add_argument("--pipeline-effects", action="store_true",
                    help="overlap async bind writes with the next "
                         "control-plane turn instead of draining between "
                         "turns")
    ap.add_argument("--action-deadline", type=float, default=None,
                    metavar="SECS",
                    help="contain any scheduling action exceeding this "
                         "deadline (faulthandler stack dump + statement "
                         "discard; remaining actions still run). Default: "
                         "no deadline")
    ap.add_argument("--breaker-failures", type=int, default=3,
                    help="consecutive device-solver failures that open "
                         "the circuit breaker (host-oracle fallback)")
    ap.add_argument("--breaker-cooldown", type=float, default=30.0,
                    metavar="SECS",
                    help="seconds the breaker stays open before a "
                         "half-open probe re-tries the device path")
    ap.add_argument("--sim-record", metavar="PATH",
                    help="append every cycle's decision record (binds/"
                         "evictions/pipelines/FitErrors, breaker state) "
                         "to PATH as JSONL — the live counterpart of the "
                         "simulator's golden traces")
    ap.add_argument("--sim-trace", metavar="PATH",
                    help="drive this control plane from a sim workload "
                         "trace (volcano_tpu.sim JSONL): arrivals submit "
                         "as Jobs when their cycle comes due")
    ap.add_argument("--solver-mode", default=None,
                    choices=["packed", "sharded", "auto"],
                    help="device-solver routing when the scheduler conf "
                         "leaves the allocate mode implicit: packed = "
                         "single-device device-resident arena, sharded = "
                         "node-axis shard_map solver over the sharded "
                         "arena, auto = shard exactly when the padded "
                         "problem's device-resident footprint (one full "
                         "upload at the measured layout) exceeds "
                         "--sharded-byte-budget bytes per device")
    ap.add_argument("--sharded-byte-budget", type=int,
                    default=256 * 1024 * 1024, metavar="BYTES",
                    help="per-device resident-state budget for "
                         "--solver-mode auto (default 256 MiB; the first "
                         "session always runs packed — no layout has "
                         "been measured yet)")
    ap.add_argument("--reschedule-interval", type=int, default=0,
                    metavar="N",
                    help="enable the global rescheduler without a conf "
                         "edit: run the device-solved defrag pass every "
                         "N scheduling cycles (0 = off; a conf naming "
                         "the reschedule action places it explicitly). "
                         "Conf-file equivalent: reschedule.interval in "
                         "the action's configurations block")
    ap.add_argument("--reschedule-max-moves", type=int, default=None,
                    metavar="K",
                    help="migration budget per defrag plan (default 8; "
                         "conf: reschedule.maxMoves)")
    ap.add_argument("--reschedule-max-disruption-per-job", type=int,
                    default=None, metavar="K",
                    dest="reschedule_max_disruption",
                    help="PDB-style per-job disruption cap per plan "
                         "(default 1; conf: reschedule.maxDisruptionPerJob)")
    ap.add_argument("--reschedule-min-improvement", type=float,
                    default=None, metavar="FRAC",
                    dest="reschedule_min_improvement",
                    help="minimum stranded-fraction improvement below "
                         "which a plan is rejected as no-op churn "
                         "(default 0.01; conf: reschedule.minImprovement)")
    args = ap.parse_args(argv)

    if args.store_replica_of:
        if not args.serve_replica:
            ap.error("--store-replica-of requires --serve-replica "
                     "(a replica exists to serve reads)")
        return run_replica(
            args.store_replica_of, args.serve_replica,
            metrics_port=args.metrics_port,
            admission_lanes=args.admission_lanes,
            admission_queue_wait_ms=args.admission_queue_wait_ms)
    if args.serve_replica:
        ap.error("--serve-replica requires --store-replica-of")

    conf = None
    if args.conf:
        with open(args.conf) as f:
            conf = f.read()
    sa = Standalone(scheduler_conf=conf, period=args.period,
                    serve_webhooks_tls=args.serve_webhooks,
                    sidecar_path=args.sidecar,
                    metrics_port=args.metrics_port,
                    serve_store=args.serve_store,
                    webhook_client_ca=args.webhook_client_ca,
                    webhook_bind=args.webhook_bind,
                    scheduler_name=args.scheduler_name,
                    default_queue=args.default_queue,
                    percentage_of_nodes_to_find=args.percentage_nodes_to_find,
                    leader_elect=args.leader_elect,
                    compile_cache_dir=args.compile_cache_dir,
                    prewarm=args.prewarm,
                    pipeline_solver=not args.serial_solver,
                    pipeline_effects=args.pipeline_effects,
                    action_deadline_s=args.action_deadline,
                    breaker_failures=args.breaker_failures,
                    breaker_cooldown_s=args.breaker_cooldown,
                    sim_record=args.sim_record,
                    sim_trace=args.sim_trace,
                    solver_mode=args.solver_mode,
                    sharded_byte_budget=args.sharded_byte_budget,
                    reschedule_interval=args.reschedule_interval,
                    reschedule_max_moves=args.reschedule_max_moves,
                    reschedule_max_disruption=args.reschedule_max_disruption,
                    reschedule_min_improvement=args.reschedule_min_improvement,
                    store_data_dir=args.store_data_dir,
                    store_fsync=args.store_fsync,
                    store_fsync_interval_s=args.store_fsync_interval,
                    store_snapshot_every=args.store_snapshot_every,
                    store_shards=args.store_shards,
                    store_shard_procs=args.store_shard_procs,
                    controller_shard_workers=args.controller_shard_workers,
                    admission_lanes=args.admission_lanes,
                    admission_queue_wait_ms=args.admission_queue_wait_ms,
                    controllers_read_endpoint=args.controllers_read_endpoint)
    if args.jobs_dir:
        import glob
        import os
        for path in sorted(glob.glob(os.path.join(args.jobs_dir, "*.yaml"))):
            with open(path) as f:
                sa.apply_job_yaml(f.read())
    print(f"volcano-tpu standalone up; metrics on "
          f":{sa.metrics_server.port}"
          + (f"; store on {sa.store_server.address}"
             if sa.store_server else ""), flush=True)
    # graceful SIGTERM: stop the loop; the finally below drains the
    # async bind effectors and only then releases the HA lease, so a
    # standby's takeover never races this process's in-flight binds
    import signal
    signal.signal(signal.SIGTERM,
                  lambda *_a: sa._stop.set())
    try:
        sa.run()
    except KeyboardInterrupt:
        pass
    finally:
        sa.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
