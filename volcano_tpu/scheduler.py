"""Scheduler: the periodic session loop (reference pkg/scheduler/scheduler.go:39-110).

Each cycle: load (possibly hot-reloaded) conf -> OpenSession -> run each
configured action -> CloseSession. The conf file is watched by mtime (the
reference uses fsnotify; polling keeps this dependency-free).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from . import actions as _actions  # noqa: F401  (registers actions)
from . import plugins as _plugins  # noqa: F401  (registers plugins)
from .cache import SchedulerCache
from .conf import DEFAULT_SCHEDULER_CONF, load_scheduler_conf
from .framework import close_session, get_action, open_session
from .metrics import metrics
from .resilience import ActionTimeout

log = logging.getLogger(__name__)

DEFAULT_SCHEDULE_PERIOD = 1.0  # seconds (options.go:83)


class Scheduler:
    def __init__(self, cache: SchedulerCache,
                 scheduler_conf: Optional[str] = None,
                 conf_path: Optional[str] = None,
                 period: float = DEFAULT_SCHEDULE_PERIOD,
                 percentage_of_nodes_to_find: int = 100,
                 compile_cache_dir: Optional[str] = None,
                 prewarm: bool = False,
                 pipeline_solver: bool = True,
                 action_deadline_s: Optional[float] = None,
                 breaker_failures: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 solver_mode: Optional[str] = None,
                 sharded_byte_budget: int = 0,
                 reschedule_interval: int = 0,
                 reschedule_max_moves: Optional[int] = None,
                 reschedule_max_disruption: Optional[int] = None,
                 reschedule_min_improvement: Optional[float] = None):
        # adaptive host-loop node sampling knob, instance-scoped
        # (cmd/scheduler/app/options/options.go:37-40)
        from .utils import NodeSampler
        self.node_sampler = NodeSampler(percentage_of_nodes_to_find)
        self.cache = cache
        self.period = period
        self.conf_path = conf_path
        self._conf_mtime = 0.0
        self._conf_text = scheduler_conf or DEFAULT_SCHEDULER_CONF
        self._conf_bad_text: Optional[str] = None
        self.actions = []
        self.tiers = []
        self.configurations = []
        self.load_conf()
        # resilience wiring (volcano_tpu.resilience): the device-path
        # circuit breaker lives on the CACHE so sessions and all
        # solver-dispatching actions share one failure account, and the
        # optional per-action deadline watchdog contains hung actions
        # (None = actions run inline, exactly the pre-watchdog path)
        from .resilience import ActionWatchdog, CircuitBreaker
        if getattr(cache, "breaker", None) is None:
            cache.breaker = CircuitBreaker(
                "device-solver", failure_threshold=breaker_failures,
                cooldown_s=breaker_cooldown_s)
        self.action_deadline_s = action_deadline_s
        self._watchdog = ActionWatchdog(action_deadline_s) \
            if action_deadline_s else None
        # --solver-mode preference (None keeps per-action conf routing):
        # "packed" pins the single-device solver, "sharded" the node-axis
        # shard_map solver over the sharded arena, "auto" shards exactly
        # when the padded problem's device-resident footprint exceeds the
        # per-device byte budget (framework.interface.Action.resolve_mode)
        if solver_mode:
            cache.solver_mode = solver_mode
        if sharded_byte_budget:
            cache.sharded_byte_budget = int(sharded_byte_budget)
        # --reschedule-* deployment flags: a positive interval opts the
        # global rescheduler in without a conf edit (load_conf appends the
        # action when the conf's actions string doesn't name it); the
        # bounding knobs become the action's defaults, per-action conf
        # arguments still win (reschedule/action.py DEFAULTS)
        self._reschedule_enabled = reschedule_interval > 0
        if self._reschedule_enabled or reschedule_max_moves is not None \
                or reschedule_max_disruption is not None \
                or reschedule_min_improvement is not None:
            opts = dict(getattr(cache, "reschedule_opts", None) or {})
            if reschedule_interval > 0:
                opts["interval"] = int(reschedule_interval)
            if reschedule_max_moves is not None:
                opts["max_moves"] = int(reschedule_max_moves)
            if reschedule_max_disruption is not None:
                opts["max_disruption_per_job"] = \
                    int(reschedule_max_disruption)
            if reschedule_min_improvement is not None:
                opts["min_improvement"] = float(reschedule_min_improvement)
            cache.reschedule_opts = opts
            self.load_conf()  # re-apply: the first load ran pre-flag
        # compile-and-dispatch pipeline (ops.precompile): persistent
        # on-disk XLA executable cache (explicit dir or
        # $VOLCANO_COMPILE_CACHE_DIR), background next-bucket pre-warm,
        # and the allocate action's dispatch/collect overlap. All three
        # are pure-latency features — scheduling decisions are identical
        # with them on or off (tests/test_precompile.py parity).
        from .ops import precompile as _pc
        self.compile_cache_dir = _pc.configure_compilation_cache(
            compile_cache_dir)
        cache.pipeline_solver = bool(pipeline_solver)
        if prewarm and getattr(cache, "prewarmer", None) is None:
            cache.prewarmer = _pc.BucketPrewarmer()
        if prewarm or self.compile_cache_dir:
            _pc.watcher.install()
        self._compile_totals = _pc.watcher.session_totals()
        # last-exported delta-watch counter snapshot (client/remote.py
        # delta_stats accumulates forever; the registry counters get the
        # per-export increment)
        self._delta_totals: dict = {}

    # -- conf hot reload (scheduler.go:112-170) -----------------------------

    def load_conf(self) -> None:
        text = self._conf_text
        if self.conf_path and os.path.exists(self.conf_path):
            mtime = os.path.getmtime(self.conf_path)
            if mtime != self._conf_mtime:
                self._conf_mtime = mtime
                with open(self.conf_path) as f:
                    text = f.read()
        if text == self._conf_bad_text:
            return  # known-bad reload, already logged: keep the last good
        try:
            conf = load_scheduler_conf(text)
            acts = []
            for name in conf.actions:
                action = get_action(name)
                if action is None:
                    raise ValueError(f"failed to find action {name}")
                acts.append(action)
        except Exception:
            if not self.actions:
                raise  # first load: there is no last-good conf to keep
            # last-good retention: a malformed hot-reloaded conf must not
            # raise out of every cycle until someone fixes the file —
            # keep scheduling on the previous conf, log once per change
            self._conf_bad_text = text
            metrics.conf_load_errors.inc()
            log.exception("scheduler conf reload failed; keeping the "
                          "last good conf")
            return
        self._conf_bad_text = None
        self._conf_text = text
        self.actions = acts
        self.tiers = conf.tiers
        self.configurations = conf.configurations
        # --reschedule-interval opt-in: append the rescheduler when the
        # conf's actions string doesn't name it (and keep it appended
        # across hot reloads); a conf that DOES name `reschedule` places
        # it explicitly and is left alone
        if getattr(self, "_reschedule_enabled", False) \
                and all(a.name() != "reschedule" for a in self.actions):
            resched = get_action("reschedule")
            if resched is not None:
                self.actions = list(self.actions) + [resched]

    # -- the loop -----------------------------------------------------------

    def run_once(self) -> None:
        # Keep collector pauses out of the scheduling cycle: a 10k-pod
        # burst churns enough objects that a mid-replay gen-2 GC adds
        # hundreds of ms of jitter to exactly the latency the e2e
        # histogram tracks. Collection happens between cycles instead
        # (run() sleeps out the remainder of the period; see _maybe_gc).
        import gc
        gc_was_enabled = gc.isenabled()
        if gc_was_enabled:
            gc.disable()
        try:
            self._run_once_inner()
        finally:
            if gc_was_enabled:
                gc.enable()

    def _maybe_gc(self) -> None:
        """Between-cycles housekeeping: collect the young generations every
        cycle, and the full heap periodically — gen 2 never auto-collects
        while GC is disabled inside cycles, so without the periodic full
        pass promoted cyclic garbage would accumulate for the life of the
        process."""
        import gc
        self._gc_cycles = getattr(self, "_gc_cycles", 0) + 1
        if self._gc_cycles % 20 == 0:
            gc.collect()
        else:
            gc.collect(1)

    def _run_once_inner(self) -> None:
        t0 = time.perf_counter()
        self.load_conf()
        ssn = open_session(self.cache, self.tiers, self.configurations)
        ssn.node_sampler = self.node_sampler
        timing = {}
        t_open = time.perf_counter()
        timing["open_ms"] = (t_open - t0) * 1e3
        try:
            for epoch, action in enumerate(self.actions):
                ta = time.perf_counter()
                name = action.name()
                ssn._action_epoch = epoch
                try:
                    self._execute_action(ssn, action)
                except ActionTimeout:
                    # deadline breach: the watchdog already dumped stacks;
                    # roll the abandoned action's statements back, fence
                    # its epoch so a zombie commit becomes a discard, and
                    # run the REMAINING actions of this cycle
                    ssn._contained_epochs.add(epoch)
                    n = ssn.discard_open_statements()
                    timing[f"{name}_timeout"] = 1.0
                    metrics.action_timeouts_total.inc(
                        labels={"action": name})
                    log.error("action %s exceeded its deadline; contained "
                              "(%d open statement(s) discarded), running "
                              "the remaining actions", name, n)
                except Exception:
                    # a throwing action is contained the same way: its
                    # uncommitted statements discard and the cycle goes on
                    # (the reference contains per-cycle errors identically
                    # — one bad action must not starve backfill forever)
                    n = ssn.discard_open_statements()
                    timing[f"{name}_error"] = 1.0
                    metrics.action_failures_total.inc(
                        labels={"action": name})
                    log.exception("action %s failed; contained (%d open "
                                  "statement(s) discarded), running the "
                                  "remaining actions", name, n)
                dt = time.perf_counter() - ta
                timing[f"{name}_ms"] = dt * 1e3
                metrics.action_scheduling_latency.observe(
                    dt * 1e6, labels={"action": name})
            # the allocate action's internal decomposition when it ran in
            # solver mode (flatten/solve/replay)
            for k, v in (ssn.solver_options.get("timing") or {}).items():
                timing[k] = v
        finally:
            tc = time.perf_counter()
            close_session(ssn)
            timing["close_ms"] = (time.perf_counter() - tc) * 1e3
        total = (time.perf_counter() - t0) * 1e3
        timing["total_ms"] = total
        self._export_pipeline_metrics(timing)
        self.last_cycle_timing = timing
        metrics.e2e_scheduling_latency.observe(total)

    def _execute_action(self, ssn, action) -> None:
        """Run one action, inline or under the deadline watchdog; the
        slow_action fault point lets the chaos harness simulate a hang."""
        from .resilience import faults

        def run():
            faults.fire("slow_action")
            action.execute(ssn)

        if self._watchdog is None:
            run()
        else:
            self._watchdog.run(action.name(), run)

    #: timing keys exported per cycle as the volcano_session_phase_ms
    #: gauge — the flatten/upload/solve/replay decomposition the compile
    #: pipeline work optimizes (upload = pack + delta_plan host share)
    _PHASE_KEYS = ("open_ms", "flatten_ms", "pack_ms", "delta_plan_ms",
                   "dispatch_ms", "overlap_ms", "readback_ms", "solve_ms",
                   "replay_ms", "close_ms", "total_ms")

    def _export_pipeline_metrics(self, timing: dict) -> None:
        """Surface per-phase latency and the cycle's compile accounting in
        both the metrics registry and last_cycle_timing: a full-solve XLA
        compile landing on the session thread is THE tail-latency event
        this scheduler exists to avoid, so it must be first-class
        observable, not a mystery spike in total_ms."""
        for key in self._PHASE_KEYS:
            if key in timing:
                metrics.session_phase_ms.set(
                    timing[key], labels={"phase": key[:-3]})
        # event-sourced flatten accounting (ops.arrays FlattenCache
        # ledger): which assembly path this cycle took, how many rows the
        # event patch touched, the patch-vs-full latency split, and the
        # fallback ladder's reason counters — exported alongside the
        # per-phase gauges because a cycle silently degrading from
        # O(events) to O(cluster) is exactly the regression these exist
        # to catch
        fc = getattr(self.cache, "flatten_cache", None)
        if fc is not None and getattr(fc, "events_enabled", False) \
                and "flatten_mode" in timing:
            mode = timing["flatten_mode"]
            metrics.flatten_cycles_total.inc(labels={"mode": mode})
            metrics.flatten_events_applied.set(
                timing.get("flatten_events_applied", 0.0))
            rows = timing.get("flatten_rows_patched", 0.0)
            metrics.flatten_rows_patched.set(rows)
            if rows:
                metrics.flatten_rows_patched_total.inc(rows)
            if "flatten_patch_ms" in timing:
                metrics.flatten_patch_ms.set(timing["flatten_patch_ms"])
            if "flatten_full_ms" in timing:
                metrics.flatten_full_ms.set(timing["flatten_full_ms"])
            reason = timing.get("flatten_fallback_reason")
            if reason:
                metrics.flatten_fallbacks_total.inc(
                    labels={"reason": str(reason)})
        # event-sourced ordering accounting (ops.ordering OrderCache):
        # same shape as the flatten family — which path the cycle's
        # ordering pass took, how many job entries it patched, the
        # event-vs-full latency split, and the typed fallback counters
        ocache = getattr(self.cache, "order_cache", None)
        if ocache is not None and "order_mode" in timing:
            mode = timing["order_mode"]
            metrics.order_cycles_total.inc(labels={"mode": mode})
            patched = timing.get("order_entries_patched", 0.0)
            metrics.order_entries_patched.set(patched)
            if patched and mode == "event":
                metrics.order_entries_patched_total.inc(patched)
            if "order_ms" in timing:
                if mode in ("reuse", "event"):
                    metrics.order_ms.set(timing["order_ms"])
                else:
                    metrics.order_full_ms.set(timing["order_ms"])
            reason = timing.get("order_fallback_reason")
            if reason:
                metrics.order_fallbacks_total.inc(
                    labels={"reason": str(reason)})
        # delta-watch wire accounting (client/remote.py delta_stats):
        # patch frames applied straight onto the mirror vs object-path
        # bytes, the decode-vs-apply ms split, and the interning-table
        # peak — the numbers that say whether the delta negotiation is
        # engaged and what it is saving. Fallback REASONS are counted at
        # the fallback site itself (volcano_delta_fallbacks_total).
        ds = getattr(getattr(self.cache, "cluster", None),
                     "delta_stats", None)
        if ds is not None and (ds["frames"] or ds["bytes_object"]):
            prev = self._delta_totals
            for key, counter in (
                    ("frames", metrics.delta_frames_total),
                    ("events", metrics.delta_patches_applied_total),
                    ("fields", metrics.delta_fields_applied_total)):
                d = ds[key] - prev.get(key, 0)
                if d > 0:
                    counter.inc(d)
            for key, mode in (("bytes_delta", "delta"),
                              ("bytes_object", "object")):
                d = ds[key] - prev.get(key, 0)
                if d > 0:
                    metrics.delta_stream_bytes_total.inc(
                        d, labels={"mode": mode})
            metrics.delta_decode_ms.set(ds["decode_ms"])
            metrics.delta_apply_ms.set(ds["apply_ms"])
            metrics.delta_vocab_size.set(ds["vocab"])
            self._delta_totals = {
                k: ds[k] for k in ("frames", "events", "fields",
                                   "bytes_delta", "bytes_object")}
            timing["delta_events_applied"] = float(ds["events"])
            timing["delta_decode_ms"] = ds["decode_ms"]
            timing["delta_apply_ms"] = ds["apply_ms"]
        from .ops.precompile import watcher
        c, s = watcher.session_totals()
        prev_c, prev_s = self._compile_totals
        self._compile_totals = (c, s)
        timing["session_compiles"] = float(c - prev_c)
        timing["session_compile_s"] = s - prev_s
        timing["compile_cache_hits"] = float(watcher.cache_hits)
        # device-resident arena accounting (ops.device_cache), exported
        # PER SOLVER MODE: a sharded session's wire bytes land on the
        # sharded arena's series, never on the packed one — wire bytes
        # per steady session and the hit rate are the two numbers that
        # say whether the RTT-floor amortization is actually engaged
        # (per-cycle bytes come from the allocate action's timing; the
        # gauges are each arena's cumulative view)
        active_mode = timing.get("arena_mode")
        for mode, attr in (("packed", "device_cache"),
                           ("sharded", "sharded_device_cache")):
            dc = getattr(self.cache, attr, None)
            if dc is None or not getattr(dc, "sessions", 0):
                continue
            lbl = {"mode": mode}
            if mode == active_mode or active_mode is None:
                timing["arena_hit_rate"] = dc.arena_hit_rate
            per_cycle = (timing.get("arena_bytes_shipped",
                                    dc.last_shipped_bytes)
                         if mode == active_mode else dc.last_shipped_bytes)
            metrics.arena_bytes_shipped.set(per_cycle, labels=lbl)
            metrics.arena_bytes_shipped_total.set(
                dc.total_shipped_bytes, labels=lbl)
            metrics.arena_hit_rate.set(dc.arena_hit_rate, labels=lbl)
            metrics.arena_sessions_total.set(
                dc.delta_sessions, labels={"outcome": "delta",
                                           "mode": mode})
            metrics.arena_sessions_total.set(
                dc.full_ships, labels={"outcome": "full", "mode": mode})
            metrics.arena_invalidations_total.set(
                dc.invalidations, labels=lbl)
            metrics.arena_params_repins_total.set(
                dc.params_repins, labels=lbl)
            if mode == "sharded":
                for d, b in enumerate(
                        getattr(dc, "last_shard_bytes", ())):
                    metrics.arena_shard_bytes_shipped.set(
                        b, labels={"shard": str(d)})
        pw = getattr(self.cache, "prewarmer", None)
        if pw is not None:
            timing["prewarm_completions"] = float(pw.completions)
        br = getattr(self.cache, "breaker", None)
        if br is not None:
            # the degradation ladder made observable per cycle: 0=closed
            # (device path live), 1=half-open probe, 2=open (host oracle)
            timing["breaker_state"] = float(br.state_code)
            timing["breaker_fallback_cycles"] = float(br.fallback_cycles)

    def shadow_cycle(self) -> None:
        """One write-free scheduling cycle against the live mirror: the
        warm-standby trick. The full session pipeline — snapshot, flatten,
        solve, replay — runs with every effector swapped for a fake, so
        the standby's process-local XLA executables, flatten/device
        caches and BucketPrewarmer are exactly as hot as the leader's,
        and the first post-takeover cycle pays zero solver compiles.
        Afterwards every mirror mutation the fake-committed binds/evicts
        made is resynced from store truth, and podgroups are re-read, so
        the mirror is byte-identical to before the shadow ran."""
        import copy

        from .cache.fakes import (
            FakeBinder, FakeEvictor, FakeStatusUpdater, FakeVolumeBinder,
        )

        cache = self.cache
        saved = (cache.binder, cache.evictor, cache.status_updater,
                 cache.volume_binder, cache.bind_journal,
                 getattr(cache, "decision_recorder", None))
        shadow_binder, shadow_evictor = FakeBinder(), FakeEvictor()
        cache.binder, cache.evictor = shadow_binder, shadow_evictor
        cache.status_updater = FakeStatusUpdater()
        cache.volume_binder = FakeVolumeBinder()
        cache.bind_journal = None
        cache.decision_recorder = None
        # JobInfo clones SHARE the pod_group object with the mirror (and,
        # in-process, with the store): give each job a private copy so
        # the shadow session's phase flips/conditions can't leak out.
        # Under the store lock: watch deliveries mutate cache.jobs
        # concurrently on a remote mirror.
        with cache.cluster.locked():
            for job in list(cache.jobs.values()):
                if job.pod_group is not None:
                    job.set_pod_group(copy.deepcopy(job.pod_group))
        try:
            self.load_conf()
            ssn = open_session(self.cache, self.tiers, self.configurations)
            ssn.node_sampler = self.node_sampler
            try:
                for epoch, action in enumerate(self.actions):
                    ssn._action_epoch = epoch
                    try:
                        self._execute_action(ssn, action)
                    except Exception:
                        ssn.discard_open_statements()
                        log.exception("shadow cycle action %s failed "
                                      "(contained)", action.name())
            finally:
                close_session(ssn)
        except Exception:
            log.exception("shadow cycle failed")
        finally:
            # drain BEFORE restoring: an async bind effect reads
            # cache.binder at run time, and must still see the fake
            try:
                cache.wait_for_effects()
            except Exception:  # noqa: BLE001
                log.exception("shadow cycle effect drain failed")
            (cache.binder, cache.evictor, cache.status_updater,
             cache.volume_binder, cache.bind_journal,
             cache.decision_recorder) = saved
            # undo the fake-committed mirror mutations from store truth;
            # resync the STORED task (its node_name reflects the fake
            # bind) so the node-side accounting unwinds too
            from .api import TaskInfo
            for pod in list(shadow_binder.bound_pods) \
                    + list(shadow_evictor.evicted_pods):
                ti = TaskInfo(pod)
                cache.resync_task(cache._stored_task(ti) or ti)
            cache.process_resync_tasks()
            try:
                for pg in cache.cluster.list("podgroups"):
                    cache.set_pod_group(pg)
            except Exception:  # noqa: BLE001 — store briefly away: mirror
                log.exception("shadow cycle podgroup refresh failed")
            # re-baseline the compile accounting: executables built during
            # the shadow belong to the standby era, so the first REAL
            # post-takeover cycle reports session_compiles == 0 when the
            # warm-up did its job (the failover bench's assertion)
            from .ops.precompile import watcher
            self._compile_totals = watcher.session_totals()

    def run_with_leader_election(self, stop, lock_name: str = "volcano",
                                 identity: Optional[str] = None,
                                 lease_duration: Optional[float] = None,
                                 renew_deadline: Optional[float] = None,
                                 retry_period: Optional[float] = None,
                                 warm_standby: bool = True) -> None:
        """HA mode (cmd/scheduler/app/server.go:85-145): only the lease
        holder schedules; standbys poll the lease and take over on expiry.
        The lease timings are overridable (tests shrink them to fail over
        in seconds; the defaults match the reference's 15/10/5).

        Crash-safe failover ladder (Borg/Omega, PAPERS.md):

        - **fencing** — every effector write carries this elector's lease
          token (cache.install_fencing); a deposed leader's late commit
          is a FencedError, not a split-brain bind;
        - **bind-intent journal** — the leader journals each decided bind
          wave before dispatching it (resilience/recovery.py), and sweeps
          confirmed intents once per cycle;
        - **recovery** — at every leadership acquisition the surviving
          intents reconcile against pod truth (adopt / re-drive) BEFORE
          the first cycle;
        - **warm standby** — the mirror subscribes immediately (not at
          first leadership) and, with ``warm_standby``, the standby runs
          write-free shadow cycles so takeover starts with hot compile/
          flatten caches: under one lease duration to the first bind,
          zero solver compiles in the first post-takeover cycle;
        - **drain-then-release** — on stop, the lease is released only
          after the async bind effectors drained.

        Lease renewal runs on its own thread at the elector's retry period
        (like client-go's renew loop), so a long scheduling cycle or a long
        schedule-period can't blow the renew deadline."""
        import threading

        from .resilience.recovery import (
            BindIntentJournal, reconcile_bind_intents,
        )
        from .utils import LeaderElector, LeaseLock
        from .utils.leader_election import (
            LEASE_DURATION, RENEW_DEADLINE, RETRY_PERIOD,
        )

        # a read-tiered cache cluster (client.readtier.ReadTierStore)
        # still arbitrates its lease — and replays the dead leader's
        # intents — against the PRIMARY: takeover truth never rides a
        # replica's staleness
        write = getattr(self.cache.cluster, "write_store",
                        self.cache.cluster)
        elector = LeaderElector(
            LeaseLock(write, lock_name), identity=identity,
            lease_duration=lease_duration or LEASE_DURATION,
            renew_deadline=renew_deadline or RENEW_DEADLINE,
            retry_period=retry_period or RETRY_PERIOD)
        self._elector = elector
        self.cache.install_fencing(elector.fencing_token)
        journal = BindIntentJournal(self.cache.fenced_cluster,
                                    identity=elector.identity)
        renewer = threading.Thread(target=elector.run,
                                   args=(stop,), kwargs={
                                       "release_on_stop": False},
                                   name="leader-elector", daemon=True)
        renewer.start()
        # warm standby: the mirror subscribes NOW, leader or not
        self.cache.run()
        self.cache.wait_for_cache_sync()
        was_leader = False
        last_shadow = 0.0
        while not stop.is_set():
            if elector.is_leader:
                if not was_leader:
                    # takeover: settle the dead leader's journaled binds
                    # before scheduling anything, then settle-or-abandon
                    # its in-flight migration waves (reschedule/intent.py:
                    # swallowed evictions are ABANDONED, never re-driven)
                    try:
                        reconcile_bind_intents(write,
                                               elector.fencing_token)
                        from .reschedule import reconcile_migration_intents
                        reconcile_migration_intents(write,
                                                    elector.fencing_token)
                    except Exception:
                        log.exception("bind/migration-intent recovery "
                                      "failed; retrying before the first "
                                      "cycle")
                        stop.wait(0.05)
                        continue
                    self.cache.bind_journal = journal
                    was_leader = True
                self.cache.process_resync_tasks()
                try:
                    self.run_once()
                except Exception:
                    log.exception("scheduling cycle failed")
                journal.sweep()
                self._maybe_gc()
                stop.wait(self.period)
            else:
                if was_leader:
                    was_leader = False
                    self.cache.bind_journal = None
                if warm_standby \
                        and time.time() - last_shadow >= self.period:
                    self.shadow_cycle()
                    last_shadow = time.time()
                stop.wait(0.05)
        # SIGTERM contract: land the in-flight binds, then hand the lease
        # over — the standby must not take over around live writes
        self.cache.wait_for_effects()
        elector.release()
        renewer.join(timeout=2 * elector.retry_period)

    def run(self, stop_after: Optional[int] = None) -> None:
        """Run the periodic loop; stop_after bounds cycles for tests.

        The loop deliberately never blocks on the cache's async bind
        effectors: with async_effectors on, cycle N's store writes drain
        on the effector pool while cycle N+1 opens its session — the
        snapshot clone and the effector-side accounting both run behind
        the cache lock, so the overlap is race-free and the next snapshot
        always sees a consistent mirror (the writes it may not yet see
        are exactly the ones an informer-fed reference scheduler would
        also still have in flight). Standalone.run mirrors this with
        pipeline_effects=True."""
        self.cache.run()
        self.cache.wait_for_cache_sync()
        cycles = 0
        while stop_after is None or cycles < stop_after:
            start = time.time()
            self.cache.process_resync_tasks()
            try:
                self.run_once()
            except Exception:
                log.exception("scheduling cycle failed")
            cycles += 1
            if stop_after is not None and cycles >= stop_after:
                break
            self._maybe_gc()
            elapsed = time.time() - start
            if elapsed < self.period:
                time.sleep(self.period - elapsed)
