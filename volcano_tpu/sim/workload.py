"""Seeded workload generation + the JSONL trace format.

The simulator is trace-driven: a Workload is a deterministic list of
arrival events (one per job), either generated from a WorkloadSpec with a
seeded RNG or loaded from a JSONL file, so externally captured cluster
traces (Borg/Philly-style) can drive the same harness. Every event
carries everything the virtual cluster needs to emulate the job's
lifetime: gang size, queue, priority, per-task requests, per-task run
duration in virtual cycles, and optional mid-run failures.

Event line schema (one JSON object per line):

    {"t": <arrival cycle>, "kind": "job", "name": "j12",
     "namespace": "sim", "queue": "q1", "min_member": 3,
     "priority_class": "", "tasks": [
        {"cpu": "2", "memory": "2Gi", "gpu": 0,
         "duration": 11, "fail_after": null}, ...]}

A ``{"kind": "header", "spec": {...}}`` first line records the generating
spec; loaders ignore unknown keys so hand-edited or external traces stay
loadable.
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from ..models import (
    Node, Pod, PodGroup, PodGroupPhase, PodGroupSpec, PodGroupStatus,
    PriorityClass, Queue, QueueSpec,
)
from ..api.types import POD_GROUP_ANNOTATION

#: pod annotations the virtual cluster reads to emulate the lifecycle
DURATION_ANNOTATION = "sim.volcano.sh/duration-cycles"
FAIL_AFTER_ANNOTATION = "sim.volcano.sh/fail-after-cycles"


@dataclass
class WorkloadSpec:
    """Knobs for the seeded generator. Every distribution draws from ONE
    ``random.Random(seed)`` stream in a fixed order, so a spec is a
    complete, reproducible description of the workload."""

    seed: int = 0
    cycles: int = 100              # arrival horizon (cycles with arrivals)
    nodes: int = 8
    node_cpu: str = "32"
    node_mem: str = "128Gi"
    gpu_nodes: int = 0             # first K nodes also expose GPUs
    node_gpu: int = 8
    queues: Tuple[Tuple[str, int], ...] = (("q0", 1), ("q1", 2))
    arrival_rate: float = 1.5      # expected jobs per cycle (Poisson)
    gang_min: int = 1
    gang_max: int = 3
    cpu_choices: Tuple[int, ...] = (1, 2, 4)
    mem_gi_choices: Tuple[int, ...] = (1, 2, 4)
    gpu_fraction: float = 0.0      # fraction of jobs requesting 1 GPU/task
    duration_min: int = 3          # task run time, virtual cycles
    duration_max: int = 12
    fail_fraction: float = 0.0     # fraction of pods failing once mid-run
    # (name, priority value, fraction of jobs) — empty = no priorities
    priorities: Tuple[Tuple[str, int, float], ...] = ()
    namespace: str = "sim"

    def to_dict(self) -> dict:
        d = asdict(self)
        d["queues"] = [list(q) for q in self.queues]
        d["priorities"] = [list(p) for p in self.priorities]
        return d


def _poisson(rng: random.Random, lam: float) -> int:
    """Knuth's algorithm: deterministic given the rng stream."""
    if lam <= 0:
        return 0
    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


class Workload:
    """A deterministic event list + the cluster objects it runs against."""

    def __init__(self, spec: WorkloadSpec,
                 events: Optional[List[dict]] = None):
        self.spec = spec
        self.events: List[dict] = (list(events) if events is not None
                                   else self._generate())
        self._by_cycle: Dict[int, List[dict]] = {}
        for ev in self.events:
            self._by_cycle.setdefault(int(ev["t"]), []).append(ev)

    # -- generation ---------------------------------------------------------

    def _generate(self) -> List[dict]:
        s = self.spec
        rng = random.Random(s.seed)
        events: List[dict] = []
        seq = 0
        qnames = [q for q, _ in s.queues]
        for t in range(s.cycles):
            for _ in range(_poisson(rng, s.arrival_rate)):
                gang = rng.randint(s.gang_min, s.gang_max)
                queue = qnames[seq % len(qnames)] if qnames else "default"
                wants_gpu = s.gpu_fraction > 0 \
                    and rng.random() < s.gpu_fraction
                prio = ""
                for name, _value, frac in s.priorities:
                    if rng.random() < frac:
                        prio = name
                        break
                cpu = rng.choice(s.cpu_choices)
                mem = rng.choice(s.mem_gi_choices)
                tasks = []
                for _i in range(gang):
                    dur = rng.randint(s.duration_min, s.duration_max)
                    fail = None
                    if s.fail_fraction > 0 \
                            and rng.random() < s.fail_fraction:
                        fail = max(1, dur // 2)
                    tasks.append({"cpu": str(cpu), "memory": f"{mem}Gi",
                                  "gpu": 1 if wants_gpu else 0,
                                  "duration": dur, "fail_after": fail})
                events.append({"t": t, "kind": "job", "name": f"j{seq}",
                               "namespace": s.namespace, "queue": queue,
                               "min_member": gang, "priority_class": prio,
                               "tasks": tasks})
                seq += 1
        return events

    # -- access -------------------------------------------------------------

    def arrivals(self, cycle: int) -> List[dict]:
        return self._by_cycle.get(cycle, [])

    @property
    def total_pods(self) -> int:
        return sum(len(ev["tasks"]) for ev in self.events)

    # -- cluster objects ----------------------------------------------------

    def node_objects(self) -> List[Node]:
        s = self.spec
        out = []
        for i in range(s.nodes):
            rl = {"cpu": s.node_cpu, "memory": s.node_mem, "pods": 110}
            if i < s.gpu_nodes:
                rl["nvidia.com/gpu"] = s.node_gpu
            out.append(Node(name=f"n{i}", allocatable=rl,
                            capacity=dict(rl)))
        return out

    def queue_objects(self) -> List[Queue]:
        # distinct virtual creation timestamps: the queue-order
        # comparator's tiebreak must never fall through to the
        # process-local uid counter (which differs between runs)
        return [Queue(name=name, spec=QueueSpec(weight=w),
                      creation_timestamp=float(i) * 1e-4)
                for i, (name, w) in enumerate(self.spec.queues)]

    def priority_class_objects(self) -> List[PriorityClass]:
        return [PriorityClass(name=name, value=value)
                for name, value, _frac in self.spec.priorities]

    # -- trace (de)serialization --------------------------------------------

    def dump_lines(self) -> List[str]:
        lines = [json.dumps({"kind": "header", "spec": self.spec.to_dict()},
                            sort_keys=True, separators=(",", ":"))]
        lines += [json.dumps(ev, sort_keys=True, separators=(",", ":"))
                  for ev in self.events]
        return lines

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write("\n".join(self.dump_lines()) + "\n")

    @classmethod
    def load(cls, path: str) -> "Workload":
        spec = WorkloadSpec()
        events: List[dict] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                obj = json.loads(line)
                if obj.get("kind") == "header":
                    raw = obj.get("spec") or {}
                    known = {k: raw[k] for k in raw
                             if k in WorkloadSpec.__dataclass_fields__}
                    if "queues" in known:
                        known["queues"] = tuple(
                            (q, int(w)) for q, w in known["queues"])
                    if "priorities" in known:
                        known["priorities"] = tuple(
                            (n, int(v), float(fr))
                            for n, v, fr in known["priorities"])
                    spec = WorkloadSpec(**known)
                elif obj.get("kind", "job") == "job":
                    events.append(obj)
        return cls(spec, events=events)


def fragmented_workload(seed: int = 0, cycles: int = 500,
                        nodes: int = 9) -> Workload:
    """Seeded ``fragmented`` preset: the workload the rescheduler's
    defrag gain is judged on (ISSUE 8 / ROADMAP item 5).

    Three deterministic components interleave on ``nodes`` x 32-cpu
    nodes. The component rates are absolute, so ``nodes`` sets the
    operating point: the default 9 lands at ~0.80 mean utilization —
    fragmented (the 16-cpu shape regularly fits nowhere, bigs queue)
    but with landing capacity left for migrations, which is the regime
    defragmentation exists for. 6 nodes saturates (~0.88, no landing
    room); 12 idles (~0.69, nothing queues long enough to matter).

    - **long-running gangs** (cpu 8, gang 2-3, 60-140 cycles) arriving
      every few cycles — the placements that pin history;
    - **high-churn short jobs** (cpu 1-2, gang 1-2, 2-6 cycles, Poisson
      ~4/cycle) constantly opening and closing holes around them;
    - **big periodic jobs** (cpu 16, 10-20 cycles) — the fragmentation
      victims: once the longs are scattered, plenty of total free CPU
      sits stranded in sub-16 slots and the bigs queue.

    Same seed => byte-identical trace; the no-reschedule run of this
    workload is the golden baseline the reschedule-enabled run must beat
    on utilization and fragmentation_index with wait p99 no worse
    (tests/test_reschedule.py, bench.py reschedule_defrag).
    """
    spec = WorkloadSpec(
        seed=seed, cycles=cycles, nodes=nodes, node_cpu="32",
        node_mem="128Gi", queues=(("q0", 1), ("q1", 2)),
        arrival_rate=4.0, gang_min=1, gang_max=3,
        cpu_choices=(1, 2, 4, 8, 16), mem_gi_choices=(1, 2, 4),
        duration_min=2, duration_max=140)
    rng = random.Random(seed ^ 0xF4A6)
    qnames = [q for q, _ in spec.queues]
    events: List[dict] = []
    seq = 0

    def emit(t, gang, cpu, mem_gi, dur_lo, dur_hi, tag):
        nonlocal seq
        tasks = [{"cpu": str(cpu), "memory": f"{mem_gi}Gi", "gpu": 0,
                  "duration": rng.randint(dur_lo, dur_hi),
                  "fail_after": None} for _ in range(gang)]
        events.append({"t": t, "kind": "job",
                       "name": f"{tag}{seq}",
                       "namespace": spec.namespace,
                       "queue": qnames[seq % len(qnames)],
                       "min_member": gang, "priority_class": "",
                       "tasks": tasks})
        seq += 1

    for t in range(cycles):
        if t % 8 == 0:
            # long-running gang: the fragment-pinning component
            emit(t, rng.randint(2, 3), 8, 4, 50, 110, "long")
        if t % 4 == 2:
            # big single-node job: needs one mostly-free node — the
            # fragmentation victim the defrag gain is measured on
            emit(t, 1, 16, 4, 15, 30, "big")
        for _ in range(_poisson(rng, 4.0)):
            emit(t, rng.randint(1, 2), rng.choice((1, 1, 2)),
                 rng.choice((1, 2)), 2, 6, "churn")
    return Workload(spec, events=events)


#: named presets accepted by `vcctl sim --preset` / `python -m
#: volcano_tpu.sim --preset`; each returns a fully-seeded Workload
WORKLOAD_PRESETS = {
    "fragmented": fragmented_workload,
}


def build_job_crd(ev: dict):
    """One arrival event as a volcano Job CRD — the ``standalone
    --sim-trace`` path, where arrivals must take the full admission +
    job-controller route instead of raw podgroup/pod creation."""
    from ..models import Job, JobSpec, TaskSpec

    groups: Dict[tuple, int] = {}
    for t in ev["tasks"]:
        sig = (str(t.get("cpu", "1")), str(t.get("memory", "1Gi")),
               int(t.get("gpu", 0) or 0))
        groups[sig] = groups.get(sig, 0) + 1
    tasks = []
    for i, (sig, n) in enumerate(sorted(groups.items())):
        req = {"cpu": sig[0], "memory": sig[1]}
        if sig[2]:
            req["nvidia.com/gpu"] = sig[2]
        tasks.append(TaskSpec(
            name=f"task{i}", replicas=n,
            template={"spec": {"containers": [
                {"name": ev["name"], "image": "sim", "requests": req}]}}))
    return Job(
        name=ev["name"], namespace=ev.get("namespace", "sim"),
        spec=JobSpec(
            min_available=int(ev.get("min_member", 1)),
            queue=ev.get("queue", ""),
            # empty: the mutate webhook fills the control plane's
            # scheduler name (see cli.vcctl._job_from_yaml)
            scheduler_name="",
            priority_class_name=ev.get("priority_class", ""),
            tasks=tasks))


def build_job_objects(ev: dict, now: float, seq_base: float = 0.0):
    """Materialize one arrival event into (PodGroup, [Pod]) with virtual
    creation timestamps. ``seq_base`` spreads objects created in the same
    virtual instant so ordering tiebreaks never reach the process-local
    uid counter."""
    name = ev["name"]
    ns = ev.get("namespace", "sim")
    pg = PodGroup(
        name=name, namespace=ns,
        spec=PodGroupSpec(min_member=int(ev.get("min_member", 1)),
                          queue=ev.get("queue", "default"),
                          priority_class_name=ev.get("priority_class", "")),
        status=PodGroupStatus(phase=PodGroupPhase.PENDING),
        creation_timestamp=now + seq_base)
    pods = []
    for i, t in enumerate(ev["tasks"]):
        req = {"cpu": str(t.get("cpu", "1")),
               "memory": t.get("memory", "1Gi")}
        if t.get("gpu"):
            req["nvidia.com/gpu"] = int(t["gpu"])
        ann = {POD_GROUP_ANNOTATION: name,
               DURATION_ANNOTATION: str(int(t.get("duration", 5)))}
        if t.get("fail_after") is not None:
            ann[FAIL_AFTER_ANNOTATION] = str(int(t["fail_after"]))
        pods.append(Pod(
            name=f"{name}-{i}", namespace=ns, annotations=ann,
            containers=[{"requests": req}],
            priority_class_name=ev.get("priority_class", ""),
            creation_timestamp=now + seq_base + (i + 1) * 1e-6))
    return pg, pods
