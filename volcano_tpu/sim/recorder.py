"""Per-cycle decision trace, written as canonical JSONL.

One record per scheduling cycle captures every decision the control
plane made — binds and evictions at the effector boundary
(cache.RecordingBinder/RecordingEvictor), pipeline statements and
per-job FitErrors summaries from the session-close hook
(framework.close_session -> observe_session), lifecycle events injected
by the virtual cluster, and the breaker/fallback state of the cycle.

Canonical form: keys sorted, no whitespace, lists sorted, floats
rounded — so "same seed + same config => byte-identical trace" is a
meaningful equality, and a SHA-256 over the lines is a stable run
fingerprint.

Reproducibility contract: a strict recorder refuses a wall-clock time
source outright, and while a record is being composed/serialized
``time.time``/``time.monotonic`` RAISE (the wall-clock ban hook) so an
accidentally wall-derived field can never leak into a golden trace.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import time
from typing import Dict, List, Optional

from ..metrics import metrics

_WALL_CLOCKS = ("time", "monotonic", "perf_counter")


class DecisionRecorder:
    def __init__(self, clock, sink=None, strict: bool = True):
        """``clock`` is the run's time source (the virtual clock in sim
        runs; wall time is allowed only with ``strict=False``, e.g. for
        ``standalone --sim-record`` live observability traces). ``sink``
        is an optional open text file that gets each line appended."""
        if strict and clock in (time.time, time.monotonic,
                                time.perf_counter):
            raise ValueError(
                "strict DecisionRecorder requires a virtual clock, not a "
                "wall-clock time source (reproducibility contract)")
        self.clock = clock
        self.strict = strict
        self.sink = sink
        self.lines: List[str] = []
        self._sha = hashlib.sha256()
        self._cycle: Optional[int] = None
        self._reset_cycle_state()

    def _reset_cycle_state(self) -> None:
        self._vtime = 0.0
        self._binds: List[List[str]] = []
        self._evicts: List[List[str]] = []
        self._pipelines: List[List[str]] = []
        self._unsched: Dict[str, str] = {}
        self._events: Dict[str, List[str]] = {}

    # -- wall-clock ban hook -------------------------------------------------

    @contextlib.contextmanager
    def wallclock_banned(self):
        """While composing/serializing a record, wall-clock reads raise.
        No-op when strict is off (live traces timestamp with wall time by
        design)."""
        if not self.strict:
            yield
            return
        saved = {name: getattr(time, name) for name in _WALL_CLOCKS}

        def _banned(*_a, **_k):
            raise RuntimeError(
                "wall-clock read while composing a sim decision record — "
                "trace fields must derive from the virtual clock only")

        try:
            for name in _WALL_CLOCKS:
                setattr(time, name, _banned)
            yield
        finally:
            for name, fn in saved.items():
                setattr(time, name, fn)

    # -- per-cycle hooks ------------------------------------------------------

    def begin_cycle(self, cycle: int) -> None:
        self._cycle = int(cycle)
        self._reset_cycle_state()
        self._vtime = float(self.clock())

    def record_bind(self, key: str, node: str) -> None:
        self._binds.append([key, node])
        metrics.sim_decisions_total.inc(labels={"kind": "bind"})

    def record_evict(self, key: str, reason: str) -> None:
        self._evicts.append([key, reason])
        metrics.sim_decisions_total.inc(labels={"kind": "evict"})

    def record_pipeline(self, key: str, node: str) -> None:
        self._pipelines.append([key, node])
        metrics.sim_decisions_total.inc(labels={"kind": "pipeline"})

    def record_event(self, kind: str, name: str) -> None:
        """Workload/lifecycle events (arrival/complete/fail/replace) the
        virtual cluster injects — part of the trace so a divergence diff
        can tell decision drift from workload drift."""
        self._events.setdefault(kind, []).append(name)

    def observe_session(self, ssn) -> None:
        """close_session hook: pipeline statements + per-job aggregated
        FitErrors (api.unschedule_info.aggregate_fit_errors)."""
        from ..api import TaskStatus
        from ..api.unschedule_info import aggregate_fit_errors

        for uid in sorted(ssn.jobs):
            job = ssn.jobs[uid]
            for t in job.task_status_index.get(
                    TaskStatus.PIPELINED, {}).values():
                self.record_pipeline(t.key, t.node_name)
            if job.nodes_fit_errors:
                self._unsched[uid] = aggregate_fit_errors(
                    job.nodes_fit_errors, len(job.tasks))

    def end_cycle(self, timing: Optional[dict] = None) -> str:
        """Compose + append the cycle's canonical record; returns the
        line. Wall-clock reads are banned for the duration."""
        timing = timing or {}
        with self.wallclock_banned():
            rec = {
                "cycle": self._cycle,
                "vtime": round(self._vtime, 6),
                "binds": sorted(self._binds),
                "evicts": sorted(self._evicts),
                "pipelines": sorted(self._pipelines),
                "unschedulable": dict(sorted(self._unsched.items())),
                "events": {k: sorted(v)
                           for k, v in sorted(self._events.items())},
                "breaker": int(timing.get("breaker_state", 0) or 0),
                "fallback": int(bool(timing.get("host_fallback"))),
            }
            line = json.dumps(rec, sort_keys=True, separators=(",", ":"))
        self.lines.append(line)
        self._sha.update(line.encode() + b"\n")
        if self.sink is not None:
            self.sink.write(line + "\n")
            self.sink.flush()
        metrics.sim_cycles_total.inc()
        return line

    # -- trace access ---------------------------------------------------------

    def digest(self) -> str:
        return self._sha.hexdigest()

    def last_record(self) -> Optional[dict]:
        return json.loads(self.lines[-1]) if self.lines else None
