"""Deterministic trace-driven cluster simulator.

Runs the UNMODIFIED scheduler loop against an emulated cluster on a
virtual clock, records every decision as canonical JSONL, replays golden
traces with structured first-divergence diffs, and scores scheduling
quality (wait, makespan, utilization, Jain fairness, preemption churn).

``python -m volcano_tpu.sim --cycles 500 --seed 7`` prints the trace and
a final quality-report line; same seed + config => byte-identical trace.
"""

from .recorder import DecisionRecorder  # noqa: F401
from .replay import (  # noqa: F401
    SimResult, first_divergence, run_sim, verify,
)
from .score import compute as compute_score, jain_fairness  # noqa: F401
from .virtualcluster import VirtualClock, VirtualCluster, build_conf  # noqa: F401
from .workload import Workload, WorkloadSpec  # noqa: F401
