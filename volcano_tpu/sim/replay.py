"""Golden-trace record / verify with structured first-divergence diffs.

Record mode runs a sim and writes the canonical decision trace; verify
mode re-runs with the same seed + config and compares byte for byte. On
mismatch it reports the FIRST diverging cycle with a per-field diff
(lists get golden_only/actual_only sets) so a refactor that changed a
scheduling decision is pinpointed to the cycle and the decision kind,
not just "traces differ".
"""

from __future__ import annotations

import json
from typing import List, NamedTuple, Optional

from ..metrics import metrics
from . import score as score_mod
from .virtualcluster import VirtualCluster
from .workload import Workload, WorkloadSpec


class SimResult(NamedTuple):
    lines: List[str]     # canonical trace lines (no newline)
    digest: str          # sha256 over the trace
    score: dict          # quality report (score.compute)
    stats: dict          # raw VirtualCluster stats
    vc: VirtualCluster   # the finished cluster (inspection/tests)


def run_sim(spec: Optional[WorkloadSpec] = None, cycles: int = 100,
            mode: str = "solver", drain: int = 0,
            workload: Optional[Workload] = None,
            scheduler_conf: Optional[str] = None, preempt: bool = False,
            record_path: Optional[str] = None,
            solver_mode: Optional[str] = None,
            sharded_byte_budget: int = 0,
            reschedule: Optional[dict] = None) -> SimResult:
    """One full sim run. ``workload`` overrides ``spec`` (external
    traces); ``drain`` allows extra cycles for in-flight jobs to finish
    so makespan/conservation are meaningful; ``reschedule`` (a dict of
    interval / max_moves / max_disruption_per_job / min_improvement)
    enables the global rescheduler action."""
    wl = workload if workload is not None \
        else Workload(spec or WorkloadSpec())
    vc = VirtualCluster(wl, mode=mode, scheduler_conf=scheduler_conf,
                        preempt=preempt, solver_mode=solver_mode,
                        sharded_byte_budget=sharded_byte_budget,
                        reschedule=reschedule)
    lines = vc.run(cycles, drain=drain)
    sc = score_mod.compute(vc.stats, cycles=len(lines), dt=vc.dt)
    if record_path:
        with open(record_path, "w") as f:
            f.write("\n".join(lines) + "\n")
    return SimResult(lines=lines, digest=vc.recorder.digest(), score=sc,
                     stats=vc.stats, vc=vc)


def load_trace(path: str) -> List[str]:
    with open(path) as f:
        return [ln.rstrip("\n") for ln in f if ln.strip()]


def _diff_field(golden, actual):
    if isinstance(golden, list) and isinstance(actual, list):
        gset = {json.dumps(x, sort_keys=True) for x in golden}
        aset = {json.dumps(x, sort_keys=True) for x in actual}
        return {
            "golden_only": sorted(json.loads(x) for x in gset - aset),
            "actual_only": sorted(json.loads(x) for x in aset - gset),
        }
    return {"golden": golden, "actual": actual}


def first_divergence(golden: List[str],
                     actual: List[str]) -> Optional[dict]:
    """None when byte-identical; otherwise a structured report for the
    first diverging cycle."""
    for i, (g, a) in enumerate(zip(golden, actual)):
        if g == a:
            continue
        try:
            gobj, aobj = json.loads(g), json.loads(a)
        except ValueError:
            return {"cycle": i, "fields": {
                "__raw__": {"golden": g, "actual": a}}}
        fields = {}
        for key in sorted(set(gobj) | set(aobj)):
            if gobj.get(key) != aobj.get(key):
                fields[key] = _diff_field(gobj.get(key), aobj.get(key))
        return {"cycle": gobj.get("cycle", i), "fields": fields}
    if len(golden) != len(actual):
        return {"cycle": min(len(golden), len(actual)),
                "fields": {"__length__": {"golden": len(golden),
                                          "actual": len(actual)}}}
    return None


def verify(golden, **run_kwargs) -> dict:
    """Re-run with the given config and compare against a golden trace
    (path or list of lines). Returns {"ok", "divergence", "cycles",
    "digest"}."""
    golden_lines = load_trace(golden) if isinstance(golden, str) \
        else list(golden)
    result = run_sim(**run_kwargs)
    div = first_divergence(golden_lines, result.lines)
    if div is not None:
        metrics.sim_replay_divergences_total.inc()
    return {"ok": div is None, "divergence": div,
            "cycles": len(result.lines), "digest": result.digest}
