"""CLI entry: ``python -m volcano_tpu.sim``.

Prints the canonical decision trace (one JSONL record per virtual cycle)
followed by one summary line ``{"sim": {...score...}, "digest": ...}``.
Everything printed derives from the virtual clock, so the same seed and
flags produce byte-identical stdout — the property the golden-trace
tier-1 tests pin.

Modes:
  (default)        run a seeded workload, print trace + score
  --record PATH    also write the trace to PATH (golden trace)
  --verify PATH    re-run and diff against a golden trace; exit 2 on
                   divergence with a structured first-divergence report
  --trace PATH     load the workload from an external JSONL trace
                   instead of generating one
  --emit-workload PATH  write the generated workload trace (editable,
                   reloadable via --trace) and exit
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="volcano-tpu-sim")
    ap.add_argument("--cycles", type=int, default=100)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mode", default="solver",
                    choices=["solver", "host", "sequential", "sharded"],
                    help="allocate execution mode under test")
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--rate", type=float, default=1.5,
                    help="expected job arrivals per cycle (Poisson)")
    ap.add_argument("--gang-max", type=int, default=3)
    ap.add_argument("--duration-max", type=int, default=12)
    ap.add_argument("--fail-fraction", type=float, default=0.0)
    ap.add_argument("--drain", type=int, default=0,
                    help="extra cycles to let in-flight jobs finish")
    ap.add_argument("--preempt", action="store_true",
                    help="enable the preempt action")
    ap.add_argument("--record", metavar="PATH",
                    help="write the decision trace to PATH")
    ap.add_argument("--verify", metavar="PATH",
                    help="verify against a golden trace at PATH")
    ap.add_argument("--trace", metavar="PATH",
                    help="load the workload from a JSONL trace")
    ap.add_argument("--preset", default=None,
                    choices=["fragmented"],
                    help="named seeded workload preset (overrides the "
                         "generator knobs; --seed/--cycles/--nodes still "
                         "apply)")
    ap.add_argument("--reschedule-interval", type=int, default=0,
                    metavar="N",
                    help="enable the global rescheduler: run the defrag "
                         "solve every N cycles (0 = off)")
    ap.add_argument("--reschedule-max-moves", type=int, default=8,
                    help="migration budget per defrag plan")
    ap.add_argument("--reschedule-max-disruption-per-job", type=int,
                    default=1, dest="reschedule_max_disruption",
                    help="PDB-style per-job disruption cap per plan")
    ap.add_argument("--emit-workload", metavar="PATH",
                    help="write the generated workload trace and exit")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-cycle trace lines on stdout")
    args = ap.parse_args(argv)

    from .replay import run_sim, verify
    from .workload import WORKLOAD_PRESETS, Workload, WorkloadSpec

    spec = WorkloadSpec(seed=args.seed, cycles=args.cycles,
                        nodes=args.nodes, arrival_rate=args.rate,
                        gang_max=args.gang_max,
                        duration_max=args.duration_max,
                        fail_fraction=args.fail_fraction)
    conf = None
    if args.trace:
        workload = Workload.load(args.trace)
    elif args.preset:
        workload = WORKLOAD_PRESETS[args.preset](
            seed=args.seed, cycles=args.cycles, nodes=args.nodes)
        # both arms of a defrag A/B run the binpack conf: the baseline
        # must already pack as well as the scorer can, so the reschedule
        # gain measures un-done HISTORY, not a handicapped allocate
        from .virtualcluster import BINPACK_CONF
        conf = BINPACK_CONF
    else:
        workload = Workload(spec)
    reschedule = None
    if args.reschedule_interval > 0:
        reschedule = {
            "interval": args.reschedule_interval,
            "max_moves": args.reschedule_max_moves,
            "max_disruption_per_job": args.reschedule_max_disruption,
        }

    if args.emit_workload:
        workload.save(args.emit_workload)
        print(json.dumps({"workload": args.emit_workload,
                          "events": len(workload.events),
                          "pods": workload.total_pods}))
        return 0

    if args.verify:
        rep = verify(args.verify, workload=workload, cycles=args.cycles,
                     mode=args.mode, drain=args.drain,
                     preempt=args.preempt, scheduler_conf=conf,
                     reschedule=reschedule)
        print(json.dumps(rep, sort_keys=True))
        return 0 if rep["ok"] else 2

    result = run_sim(workload=workload, cycles=args.cycles,
                     mode=args.mode, drain=args.drain,
                     preempt=args.preempt, record_path=args.record,
                     scheduler_conf=conf, reschedule=reschedule)
    if not args.quiet:
        for line in result.lines:
            print(line)
    print(json.dumps({"sim": result.score, "digest": result.digest},
                     sort_keys=True, separators=(",", ":")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
