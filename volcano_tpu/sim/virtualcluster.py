"""Virtual clock + pod-lifecycle emulation around the UNMODIFIED scheduler.

The simulator drives the real control-plane stack — ClusterStore,
SchedulerCache, Scheduler.run_once with the production actions/plugins —
against an emulated cluster on a virtual clock:

- arrivals come from a Workload (seeded generator or external JSONL
  trace) as PodGroup + Pending pods;
- binds go through the real DefaultBinder (store write -> watch echo ->
  mirror accounting), wrapped in cache.RecordingBinder so every bind is
  recorded and starts the pod's virtual run clock;
- a bound pod runs for its sampled duration, completes, and releases its
  resources; pods carrying a fail-after annotation fail once mid-run and
  are replaced by a fresh Pending pod (the job controller's recreate
  semantics), feeding failures back into the scheduler as new work;
- evictions (preempt/reclaim) use the graceful-deletion path: a
  virtual-clock evictor stamps deletion_timestamp in virtual seconds and
  the kubelet stand-in (controllers.kubelet.KubeletStandin with the
  virtual clock) finalizes after grace, after which the victim is
  replaced as a real cluster's job controller would.

Nothing in the decision path reads the wall clock: creation timestamps,
deletion timestamps, and grace periods are all virtual, so the same seed
and config reproduce the same decision trace byte for byte.
"""

from __future__ import annotations

import heapq
import logging
import re
from typing import Dict, List, Optional

from ..api import Resource
from ..api.types import POD_GROUP_ANNOTATION
from ..cache import RecordingBinder, RecordingEvictor, SchedulerCache
from ..cache.cache import DefaultBinder, DefaultEvictor
from ..client.store import ClusterStore, NotFoundError
from ..conf import DEFAULT_SCHEDULER_CONF
from ..controllers import ControllerOption
from ..controllers.kubelet import KubeletStandin
from ..models import Pod
from ..scheduler import Scheduler
from .recorder import DecisionRecorder
from .workload import (
    DURATION_ANNOTATION, FAIL_AFTER_ANNOTATION, Workload, build_job_objects,
)

log = logging.getLogger(__name__)


class VirtualClock:
    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        self._now += dt


class VirtualEvictor(DefaultEvictor):
    """DefaultEvictor with the deletion timestamp taken from the virtual
    clock, so termination grace elapses in virtual seconds."""

    def __init__(self, cluster, clock):
        super().__init__(cluster)
        self._clock = clock

    def evict(self, pod, reason: str) -> None:
        pod.conditions = [c for c in pod.conditions
                          if c.get("type") != "Ready"]
        pod.conditions.append({"type": "Ready", "status": "False",
                               "reason": "Evict", "message": reason})
        if pod.deletion_timestamp is None:
            pod.deletion_timestamp = self._clock()
        self.cluster.update("pods", pod)


#: the default conf with the binpack scorer in the second tier: the conf
#: both arms of a defrag A/B run (the baseline must already pack as well
#: as the scorer can — the rescheduler's gain is un-doing HISTORY, not
#: compensating for a spread-scoring allocate)
BINPACK_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: binpack
  - name: nodeorder
"""


def build_conf(mode: str = "solver", preempt: bool = False,
               base: Optional[str] = None,
               reschedule: Optional[dict] = None) -> str:
    """Scheduler conf for a sim run: the default conf with the allocate
    execution mode pinned (solver/host/sequential/sharded), optionally
    the preempt action enabled, and optionally the reschedule action
    appended with its bounding arguments (``reschedule`` is a dict with
    any of interval / max_moves / max_disruption_per_job /
    min_improvement)."""
    text = base if base is not None else DEFAULT_SCHEDULER_CONF
    if preempt and "preempt" not in text:
        text = text.replace(
            'actions: "enqueue, allocate, backfill"',
            'actions: "enqueue, allocate, preempt, backfill"')
    blocks = []
    if mode not in (None, "", "solver"):
        block = ("- name: allocate\n"
                 f"  arguments:\n    mode: {mode}\n")
        if mode == "host":
            for act in ("preempt", "reclaim"):
                block += (f"- name: {act}\n"
                          "  arguments:\n    mode: host\n")
        blocks.append(block)
    if reschedule:
        m = re.search(r'(actions:\s*"[^"]*)"', text)
        if m and "reschedule" not in m.group(1):
            text = text[:m.end(1)] + ", reschedule" + text[m.end(1):]
        args = {
            "reschedule.interval": reschedule.get("interval", 10),
            "reschedule.maxMoves": reschedule.get("max_moves", 8),
            "reschedule.maxDisruptionPerJob":
                reschedule.get("max_disruption_per_job", 1),
            "reschedule.minImprovement":
                reschedule.get("min_improvement", 0.01),
        }
        block = "- name: reschedule\n  arguments:\n"
        for k, v in args.items():
            block += f"    {k}: {v}\n"
        blocks.append(block)
    if blocks:
        if "configurations:" in text:
            raise ValueError(
                "build_conf cannot add configurations to a conf that "
                "already has a configurations block; pass the full conf "
                "instead")
        text = text + "\nconfigurations:\n" + "".join(blocks)
    return text


class VirtualCluster:
    """The emulated cluster + the real scheduler, stepped one virtual
    cycle at a time."""

    def __init__(self, workload: Workload, mode: str = "solver",
                 scheduler_conf: Optional[str] = None, dt: float = 1.0,
                 grace_cycles: int = 2, preempt: bool = False,
                 recorder: Optional[DecisionRecorder] = None,
                 solver_mode: Optional[str] = None,
                 sharded_byte_budget: int = 0,
                 reschedule: Optional[dict] = None):
        self.workload = workload
        self.dt = float(dt)
        self.clock = VirtualClock()
        self.recorder = recorder if recorder is not None \
            else DecisionRecorder(clock=self.clock.now)
        self.store = ClusterStore()
        self.cache = SchedulerCache(self.store)
        # wall-clock finalize would fire instantly (virtual timestamps
        # look ancient to time.time()); the virtual kubelet below owns
        # eviction finalization instead
        self.cache.EVICTION_FINALIZE_GRACE = float("inf")
        # --solver-mode routing (vcctl sim): the deployment-level
        # preference applies only when the conf leaves the allocate mode
        # implicit (Action.resolve_mode), same as standalone
        if solver_mode:
            self.cache.solver_mode = solver_mode
            self.cache.sharded_byte_budget = int(sharded_byte_budget)
        self.cache.decision_recorder = self.recorder
        self.cache.binder = RecordingBinder(
            DefaultBinder(self.store), on_bind=self._on_bind)
        self.cache.evictor = RecordingEvictor(
            VirtualEvictor(self.store, self.clock.now),
            on_evict=self._on_evict)
        self.cache.run()
        self.kubelet = KubeletStandin(
            grace_seconds=grace_cycles * self.dt, clock=self.clock.now)
        self.kubelet.initialize(ControllerOption(cluster=self.store))
        self.store.watch("pods", self._on_pod_event, replay=False)
        self.sched = Scheduler(
            self.cache,
            scheduler_conf=build_conf(mode, preempt=preempt,
                                      base=scheduler_conf,
                                      reschedule=reschedule))

        # cluster objects (distinct virtual creation timestamps)
        for q in workload.queue_objects():
            self.store.apply("queues", q)
        for pc in workload.priority_class_objects():
            self.store.apply("priorityclasses", pc)
        for node in workload.node_objects():
            self.store.create("nodes", node)
        self._alloc_mcpu = sum(
            Resource.from_resource_list(n.allocatable).milli_cpu
            for n in workload.node_objects())
        # fragmentation reference slot: the workload's largest task shape
        # (free CPU on nodes that can't fit it counts as stranded)
        self._frag_ref = max(workload.spec.cpu_choices or (1,)) * 1000.0

        # lifecycle state
        self._cycle = 0
        self._heap: list = []          # (due_vtime, seq, kind, key)
        self._heap_seq = 0
        self._obj_seq = 0              # per-tick creation-timestamp spread
        self._running: Dict[str, tuple] = {}   # key -> (Resource, job, q)
        self._bind_time: Dict[str, float] = {}  # key -> virtual bind time
        self._expected_delete: set = set()
        self._replaced: Dict[str, int] = {}    # base pod name -> count
        self._job_pods: Dict[str, set] = {}    # jobkey -> pod keys ever

        # quality-score bookkeeping (all virtual-time)
        self.stats = {
            "arrive_time": {}, "ready_time": {}, "complete_time": {},
            "job_size": {}, "min_member": {}, "queue_of": {},
            "bound_count": {}, "completed_count": {},
            "binds": 0, "evictions": 0, "evictions_finalized": 0,
            "failures": 0, "migrations": 0,
            "bound_mcpu": 0.0, "released_mcpu": 0.0,
            "util_samples": [], "frag_samples": [],
            "largest_free_samples": [],
            "queue_running_mcpu": {}, "queue_service": {},
            "queue_weight": {q: w for q, w in workload.spec.queues},
        }

    # -- lifecycle hooks -----------------------------------------------------

    @staticmethod
    def _pod_req(pod) -> Resource:
        return Resource.sum_of(
            Resource.from_resource_list(c.get("requests", {}))
            for c in pod.containers)

    def _on_bind(self, pod, hostname: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        self.recorder.record_bind(key, hostname)
        st = self.stats
        now = self.clock.now()
        req = self._pod_req(pod)
        jobkey = (f"{pod.namespace}/"
                  f"{pod.annotations.get(POD_GROUP_ANNOTATION, '')}")
        queue = st["queue_of"].get(jobkey, "default")
        self._running[key] = (req, jobkey, queue)
        self._bind_time[key] = now
        st["binds"] += 1
        st["bound_mcpu"] += req.milli_cpu
        st["queue_running_mcpu"][queue] = \
            st["queue_running_mcpu"].get(queue, 0.0) + req.milli_cpu
        bc = st["bound_count"].get(jobkey, 0) + 1
        st["bound_count"][jobkey] = bc
        if jobkey not in st["ready_time"] \
                and bc >= st["min_member"].get(jobkey, 1):
            st["ready_time"][jobkey] = now
        duration = int(pod.annotations.get(DURATION_ANNOTATION, "5"))
        fail_after = pod.annotations.get(FAIL_AFTER_ANNOTATION)
        if fail_after is not None and int(fail_after) < duration:
            self._push(now + int(fail_after) * self.dt, "fail", key)
        else:
            self._push(now + duration * self.dt, "complete", key)

    def _on_evict(self, pod, reason: str) -> None:
        from ..reschedule import MIGRATION_REASON

        key = f"{pod.namespace}/{pod.name}"
        self.recorder.record_evict(key, reason)
        self.stats["evictions"] += 1
        if reason.startswith(MIGRATION_REASON):
            self.stats["migrations"] += 1

    def _push(self, due: float, kind: str, key: str) -> None:
        self._heap_seq += 1
        heapq.heappush(self._heap, (due, self._heap_seq, kind, key))

    def _release(self, key: str) -> None:
        ent = self._running.pop(key, None)
        self._bind_time.pop(key, None)
        if ent is None:
            return
        req, jobkey, queue = ent
        st = self.stats
        st["released_mcpu"] += req.milli_cpu
        st["queue_running_mcpu"][queue] = \
            st["queue_running_mcpu"].get(queue, 0.0) - req.milli_cpu

    def _replacement(self, pod, drop_fail: bool = True,
                     resume_duration: Optional[int] = None) -> Pod:
        """The job controller's recreate semantics: a failed or evicted
        pod comes back as a fresh Pending pod of the same gang. The fail
        annotation is dropped (a task fails once), so replacement chains
        terminate deterministically. ``resume_duration`` overrides the
        replacement's run time: a PLANNED migration (reschedule eviction)
        resumes from checkpoint instead of redoing the work — failures
        and preemptions keep full-restart semantics."""
        base = pod.name.split("-r")[0]
        n = self._replaced.get(base, 0) + 1
        self._replaced[base] = n
        ann = dict(pod.annotations)
        if drop_fail:
            ann.pop(FAIL_AFTER_ANNOTATION, None)
        if resume_duration is not None:
            ann[DURATION_ANNOTATION] = str(int(resume_duration))
        self._obj_seq += 1
        repl = Pod(name=f"{base}-r{n}", namespace=pod.namespace,
                   annotations=ann, containers=pod.containers,
                   priority_class_name=pod.priority_class_name,
                   creation_timestamp=self.clock.now()
                   + self._obj_seq * 1e-4)
        jobkey = (f"{pod.namespace}/"
                  f"{ann.get(POD_GROUP_ANNOTATION, '')}")
        self._job_pods.setdefault(jobkey, set()).add(
            f"{repl.namespace}/{repl.name}")
        return repl

    def _on_pod_event(self, event, obj, old) -> None:
        if event != "delete":
            return
        key = f"{obj.namespace}/{obj.name}"
        if key in self._expected_delete:
            self._expected_delete.discard(key)
            return
        if obj.deletion_timestamp is not None and key in self._running:
            # an evicted pod the virtual kubelet just finalized: release
            # its resources and feed the replacement back as new work.
            # A reschedule-reason eviction is a planned migration of a
            # checkpointed task: the replacement resumes with the
            # remaining duration (progress accrued until the eviction
            # was stamped), so the migration's cost is the grace +
            # requeue disruption, not lost work. Preemptions restart.
            resume = None
            if any(c.get("reason") == "Evict"
                   and str(c.get("message", "")).startswith("reschedule")
                   for c in obj.conditions or []):
                bind_t = self._bind_time.get(key)
                if bind_t is not None:
                    dur = int(obj.annotations.get(DURATION_ANNOTATION,
                                                  "5"))
                    ran = int(max(0.0, obj.deletion_timestamp - bind_t)
                              / self.dt)
                    resume = max(1, dur - ran)
            self._release(key)
            self.stats["evictions_finalized"] += 1
            self.recorder.record_event("evict_finalized", key)
            repl = self._replacement(obj, resume_duration=resume)
            self.store.create("pods", repl)
            self.recorder.record_event(
                "replace", f"{repl.namespace}/{repl.name}")

    # -- virtual event delivery ----------------------------------------------

    def _deliver_due(self) -> None:
        now = self.clock.now() + 1e-9
        st = self.stats
        while self._heap and self._heap[0][0] <= now:
            _, _, kind, key = heapq.heappop(self._heap)
            ns, name = key.split("/", 1)
            pod = self.store.try_get("pods", name, ns)
            if pod is None or pod.deletion_timestamp is not None \
                    or key not in self._running:
                continue  # completed/evicted/replaced under this event
            if kind == "complete":
                _, jobkey, _q = self._running[key]
                self._release(key)
                # the pod STAYS, as Succeeded, until the whole job
                # completes — gang counts terminated tasks toward
                # minAvailable (the cache's add_task keeps them on the
                # job, only node accounting skips them), so deleting a
                # finished pod early would make the gang plugin veto the
                # job's still-running/replaced siblings
                pod.phase = "Succeeded"
                self.store.update("pods", pod)
                self.recorder.record_event("complete", key)
                cc = st["completed_count"].get(jobkey, 0) + 1
                st["completed_count"][jobkey] = cc
                if cc >= st["job_size"].get(jobkey, 1 << 30):
                    st["complete_time"][jobkey] = self.clock.now()
                    self._retire_job(jobkey)
            elif kind == "fail":
                self._release(key)
                st["failures"] += 1
                self._expected_delete.add(key)
                pod.phase = "Failed"
                self.store.update("pods", pod)
                self.store.delete("pods", name, ns)
                self.recorder.record_event("fail", key)
                repl = self._replacement(pod)
                self.store.create("pods", repl)
                self.recorder.record_event(
                    "replace", f"{repl.namespace}/{repl.name}")

    def _retire_job(self, jobkey: str) -> None:
        """All tasks completed: remove the job's pods (now Succeeded) and
        its podgroup so the pending set stays bounded over long runs."""
        ns, pg_name = jobkey.split("/", 1)
        for podkey in sorted(self._job_pods.pop(jobkey, ())):
            pns, pname = podkey.split("/", 1)
            if self.store.try_get("pods", pname, pns) is not None:
                self._expected_delete.add(podkey)
                try:
                    self.store.delete("pods", pname, pns)
                except NotFoundError:
                    self._expected_delete.discard(podkey)
        try:
            self.store.delete("podgroups", pg_name, ns)
        except NotFoundError:
            pass

    # -- workload injection ----------------------------------------------------

    def _submit(self, ev: dict) -> None:
        self._obj_seq += 1
        pg, pods = build_job_objects(ev, self.clock.now(),
                                     seq_base=self._obj_seq * 1e-4)
        self._obj_seq += len(pods)
        jobkey = f"{pg.namespace}/{pg.name}"
        st = self.stats
        st["arrive_time"][jobkey] = self.clock.now()
        st["job_size"][jobkey] = len(pods)
        st["min_member"][jobkey] = pg.spec.min_member
        st["queue_of"][jobkey] = pg.spec.queue
        self.store.create("podgroups", pg)
        pod_keys = self._job_pods.setdefault(jobkey, set())
        for pod in pods:
            self.store.create("pods", pod)
            pod_keys.add(f"{pod.namespace}/{pod.name}")
        self.recorder.record_event("arrival", jobkey)

    # -- the cycle -------------------------------------------------------------

    def tick(self) -> str:
        """One virtual cycle: deliver due lifecycle events, finalize
        graceful deletions, inject arrivals, run ONE unmodified scheduler
        cycle, sample utilization, emit the cycle's trace record."""
        rec = self.recorder
        rec.begin_cycle(self._cycle)
        self._obj_seq = 0
        self._deliver_due()
        self.kubelet.process_all()
        for ev in self.workload.arrivals(self._cycle):
            self._submit(ev)
        self.cache.process_resync_tasks()
        self.sched.run_once()
        self._sample()
        line = rec.end_cycle(self.sched.last_cycle_timing)
        self.clock.advance(self.dt)
        self._cycle += 1
        return line

    def _sample(self) -> None:
        from ..reschedule import stranded_fraction

        st = self.stats
        used = sum(ni.used.milli_cpu for ni in self.cache.nodes.values())
        st["util_samples"].append(
            used / self._alloc_mcpu if self._alloc_mcpu else 0.0)
        free = [ni.idle.milli_cpu for ni in self.cache.nodes.values()
                if ni.node is not None]
        st["frag_samples"].append(
            stranded_fraction(free, self._frag_ref))
        cap = max((ni.allocatable.milli_cpu
                   for ni in self.cache.nodes.values()
                   if ni.node is not None), default=0.0)
        st["largest_free_samples"].append(
            max(free) / cap if free and cap else 0.0)
        for q, mcpu in st["queue_running_mcpu"].items():
            st["queue_service"][q] = \
                st["queue_service"].get(q, 0.0) + mcpu * self.dt

    def all_complete(self) -> bool:
        return all(j in self.stats["complete_time"]
                   for j in self.stats["arrive_time"])

    def run(self, cycles: int, drain: int = 0) -> List[str]:
        """Run ``cycles`` ticks, then up to ``drain`` extra ticks to let
        in-flight jobs finish (stops early once everything completed)."""
        lines = [self.tick() for _ in range(cycles)]
        for _ in range(drain):
            if self.all_complete():
                break
            lines.append(self.tick())
        return lines

    # -- invariants ------------------------------------------------------------

    def conservation(self) -> dict:
        """Lifecycle conservation: all bound resources are either still
        running or were released (completion/failure/eviction)."""
        running = sum(r.milli_cpu for r, _, _ in self._running.values())
        st = self.stats
        idle_ok = all(
            ni.used.milli_cpu < 1e-6 for ni in self.cache.nodes.values()
        ) if not self._running else None
        return {
            "bound_mcpu": st["bound_mcpu"],
            "released_mcpu": st["released_mcpu"],
            "running_mcpu": running,
            "balanced": abs(st["bound_mcpu"] - st["released_mcpu"]
                            - running) < 1e-6,
            "nodes_idle_when_empty": idle_ok,
        }
