"""Scheduling-quality scoring over a finished sim run.

Pure functions over the virtual cluster's stats — everything derives
from virtual time, so the score is as reproducible as the trace. The
metrics are the ones the cluster-trace literature regresses:

- job wait (arrival -> gang ready, i.e. min_member-th bind): mean/p50/p99;
- makespan (first arrival -> last completion, when the run drained);
- node utilization (mean fraction of allocatable CPU in use per cycle);
- Jain fairness index across queues over weight-normalized service
  (cpu-time integrated over the run): 1.0 = perfectly weighted-fair;
- preemption churn (non-migration evictions per successful bind) and
  failure/replace counts;
- fragmentation: the per-cycle stranded-free-capacity fraction (free
  CPU sitting on nodes too full to fit the workload's largest task
  shape; reschedule/plan.py stranded_fraction) averaged over the run,
  the mean largest-free-slot fraction, and migration churn (rescheduler
  evictions per successful bind) — the series the reschedule action's
  defrag gain is judged on.
"""

from __future__ import annotations

from typing import Optional


def _percentile(values, q: float) -> float:
    """Deterministic linear-interpolation percentile (numpy-free so the
    score path can run anywhere the recorder does)."""
    if not values:
        return 0.0
    vs = sorted(values)
    if len(vs) == 1:
        return float(vs[0])
    pos = (len(vs) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(vs) - 1)
    frac = pos - lo
    return float(vs[lo] * (1 - frac) + vs[hi] * frac)


def jain_fairness(shares) -> float:
    """(sum x)^2 / (n * sum x^2); 1.0 for equal shares (and for the
    degenerate empty/all-zero case)."""
    xs = [float(x) for x in shares]
    n = len(xs)
    sq = sum(x * x for x in xs)
    if n == 0 or sq <= 0:
        return 1.0
    s = sum(xs)
    return (s * s) / (n * sq)


def compute(stats: dict, cycles: int, dt: float = 1.0) -> dict:
    """Quality report over a VirtualCluster.stats dict (see
    virtualcluster.py for the field inventory)."""
    arrive = stats["arrive_time"]
    ready = stats["ready_time"]
    complete = stats["complete_time"]
    waits = [ready[j] - arrive[j] for j in ready if j in arrive]
    unserved = [j for j in arrive if j not in ready]

    makespan: Optional[float] = None
    if arrive and complete and len(complete) == len(arrive):
        makespan = max(complete.values()) - min(arrive.values())

    util = stats["util_samples"]
    mean_util = sum(util) / len(util) if util else 0.0

    weights = stats.get("queue_weight", {})
    service = stats.get("queue_service", {})
    norm_shares = [service.get(q, 0.0) / max(float(w), 1e-9)
                   for q, w in sorted(weights.items())]
    jfi = jain_fairness(norm_shares)

    binds = stats["binds"]
    migrations = stats.get("migrations", 0)
    # preemption churn counts preempt/reclaim victims only; the
    # rescheduler's deliberate migrations get their own column
    churn = (stats["evictions"] - migrations) / binds if binds else 0.0

    frag = stats.get("frag_samples") or []
    largest = stats.get("largest_free_samples") or []

    r = {
        "jobs_arrived": len(arrive),
        "jobs_served": len(ready),
        "jobs_completed": len(complete),
        "jobs_unserved": len(unserved),
        "pods_bound": binds,
        "wait_mean": round(sum(waits) / len(waits), 6) if waits else 0.0,
        "wait_p50": round(_percentile(waits, 0.50), 6),
        "wait_p99": round(_percentile(waits, 0.99), 6),
        "makespan": round(makespan, 6) if makespan is not None else None,
        "utilization_mean": round(mean_util, 6),
        "jfi_queues": round(jfi, 6),
        "preemption_churn": round(churn, 6),
        "fragmentation_index": round(sum(frag) / len(frag), 6)
        if frag else 0.0,
        "largest_free_slot_mean": round(sum(largest) / len(largest), 6)
        if largest else 0.0,
        "migrations": migrations,
        "migration_churn": round(migrations / binds, 6) if binds else 0.0,
        "evictions": stats["evictions"],
        "evictions_finalized": stats["evictions_finalized"],
        "failures": stats["failures"],
        "cycles": cycles,
        "virtual_seconds": round(cycles * dt, 6),
    }
    return r
