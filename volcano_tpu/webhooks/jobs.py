"""Job admission: validate + mutate (reference webhooks/admission/jobs/).

Create validation (admit_job.go:108-237): minAvailable/maxRetry/ttl >= 0,
tasks present with DNS-1123 names, no duplicate task names, policy event
and exit-code exclusivity, minAvailable <= total replicas, known plugins,
volume mount-path uniqueness, open target queue. Update validation: only
replicas and minAvailable may change. Mutation (mutate_job.go:111-160):
default queue/scheduler/task names/minAvailable.
"""

from __future__ import annotations

import re

from ..client.store import AdmissionError
from ..models import Event, Job, QueueState
from .router import (
    AdmissionOptions, AdmissionService, register_admission_service,
)

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


def _validate_policies(policies, where: str) -> None:
    seen_events = set()
    has_any = False
    for policy in policies:
        events = set(policy.events)
        if policy.event is not None:
            events.add(policy.event)
        if events and policy.exit_code is not None:
            raise AdmissionError(
                f"{where}: must not specify event and exitCode simultaneously")
        if not events and policy.exit_code is None:
            raise AdmissionError(
                f"{where}: either event or exitCode must be specified")
        if policy.exit_code is not None and policy.exit_code == 0:
            raise AdmissionError(f"{where}: 0 is not a valid error code")
        for e in events:
            if e in seen_events:
                raise AdmissionError(f"{where}: duplicate event {e.value}")
            seen_events.add(e)
        if Event.ANY in events:
            has_any = True
    if has_any and len(seen_events) > 1:
        raise AdmissionError(
            f"{where}: if there's * here, no other policy should be here")


def _validate_io(volumes) -> None:
    seen = set()
    for vol in volumes or []:
        mp = vol.get("mountPath")
        if not mp:
            raise AdmissionError("mountPath is required")
        if mp in seen:
            raise AdmissionError(f"duplicated mountPath: {mp}")
        seen.add(mp)
        if "volumeClaimName" not in vol and "volumeClaim" not in vol:
            raise AdmissionError(
                "either VolumeClaim or VolumeClaimName must be specified")


def validate_job(verb: str, job: Job, cluster,
                 opts: AdmissionOptions = None) -> Job:
    if verb == "delete":
        return job
    if verb == "update":
        old = cluster.try_get("jobs", job.name, job.namespace)
        if old is not None:
            _validate_update(old, job)
        return job

    if job.spec.min_available < 0:
        raise AdmissionError("'minAvailable' must be >= 0.")
    if job.spec.max_retry < 0:
        raise AdmissionError("'maxRetry' cannot be less than zero.")
    if job.spec.ttl_seconds_after_finished is not None \
            and job.spec.ttl_seconds_after_finished < 0:
        raise AdmissionError("'ttlSecondsAfterFinished' cannot be less than zero.")
    if not job.spec.tasks:
        raise AdmissionError("No task specified in job spec")

    total_replicas = 0
    names = set()
    for task in job.spec.tasks:
        if task.replicas < 0:
            raise AdmissionError(f"'replicas' < 0 in task: {task.name}")
        total_replicas += task.replicas
        if task.name and not _DNS1123.match(task.name):
            raise AdmissionError(
                f"task name {task.name!r} must be a valid DNS-1123 label")
        if task.name in names:
            raise AdmissionError(f"duplicated task name {task.name}")
        names.add(task.name)
        _validate_policies(task.policies, f"spec.tasks[{task.name}].policies")
        if not (task.template or {}).get("spec", {}).get("containers"):
            raise AdmissionError(
                f"task {task.name}: template must define containers")
    if total_replicas < job.spec.min_available:
        raise AdmissionError(
            "'minAvailable' should not be greater than total replicas in tasks")
    _validate_policies(job.spec.policies, "spec.policies")

    from ..controllers.job.plugins import _PLUGIN_BUILDERS
    for name in job.spec.plugins or {}:
        if name not in _PLUGIN_BUILDERS:
            raise AdmissionError(f"unable to find job plugin: {name}")

    _validate_io(job.spec.volumes)

    default_queue = opts.default_queue if opts else "default"
    queue = cluster.try_get("queues", job.spec.queue or default_queue)
    if queue is None:
        raise AdmissionError("unable to find job queue: "
                             f"{job.spec.queue or default_queue}")
    if queue.status.state != QueueState.OPEN:
        raise AdmissionError(
            f"can only submit job to queue with state `Open`, queue "
            f"`{queue.name}` status is `{queue.status.state.value}`")
    return job


def _validate_update(old: Job, new: Job) -> None:
    total = 0
    for task in new.spec.tasks:
        if task.replicas < 0:
            raise AdmissionError(f"'replicas' must be >= 0 in task: {task.name}")
        total += task.replicas
    if new.spec.min_available > total:
        raise AdmissionError(
            "'minAvailable' must not be greater than total replicas")
    if new.spec.min_available < 0:
        raise AdmissionError("'minAvailable' must be >= 0")
    if len(old.spec.tasks) != len(new.spec.tasks):
        raise AdmissionError("job updates may not add or remove tasks")
    for ot, nt in zip(old.spec.tasks, new.spec.tasks):
        if ot.name != nt.name or ot.template != nt.template:
            raise AdmissionError(
                "job updates may not change fields other than "
                "`minAvailable`, `tasks[*].replicas` under spec")
    if (old.spec.queue, old.spec.scheduler_name, old.spec.priority_class_name) \
            != (new.spec.queue, new.spec.scheduler_name,
                new.spec.priority_class_name):
        raise AdmissionError(
            "job updates may not change fields other than "
            "`minAvailable`, `tasks[*].replicas` under spec")


def mutate_job(verb: str, job: Job, cluster,
               opts: AdmissionOptions = None) -> Job:
    if verb != "create":
        return job
    if not job.spec.queue:
        job.spec.queue = opts.default_queue if opts else "default"
    if not job.spec.scheduler_name:
        job.spec.scheduler_name = opts.scheduler_name if opts \
            else "volcano"
    for i, task in enumerate(job.spec.tasks):
        if not task.name:
            task.name = f"task-{i}"
    if job.spec.min_available == 0:
        job.spec.min_available = sum(t.replicas for t in job.spec.tasks)
    return job


def register() -> None:
    # mutation runs before validation, like the reference's webhook ordering
    register_admission_service(AdmissionService(
        path="/jobs/mutate", kind="jobs", verbs=["create"], func=mutate_job))
    register_admission_service(AdmissionService(
        path="/jobs/validate", kind="jobs", verbs=["create", "update"],
        func=validate_job))
