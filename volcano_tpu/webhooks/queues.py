"""Queue admission (reference webhooks/admission/queues/).

Validate: weight >= 1, consistent hierarchy path/weights, no deletion
while podgroups reference the queue, no deleting/modifying protected
states. Mutate: default weight, reclaimable, normalized hierarchy
annotations.
"""

from __future__ import annotations

from ..api.types import HIERARCHY_ANNOTATION, HIERARCHY_WEIGHT_ANNOTATION
from ..client.store import AdmissionError
from ..models import Queue
from .router import AdmissionService, register_admission_service


def validate_queue(verb: str, queue: Queue, cluster,
                   opts=None) -> Queue:
    if verb == "delete":
        # protect the CONFIGURED default queue (the reference protects its
        # configured default): with --default-queue=team-x, deleting
        # team-x would break every queue-less job submission
        default_queue = opts.default_queue if opts is not None else "default"
        if queue.name == default_queue:
            raise AdmissionError(
                f"`{default_queue}` queue can not be deleted")
        for pg in cluster.list("podgroups"):
            if (pg.spec.queue or "default") == queue.name:
                raise AdmissionError(
                    f"queue {queue.name} has podgroup bound to it, "
                    f"cannot be deleted")
        return queue

    if queue.spec.weight < 1:
        raise AdmissionError("'weight' must be >= 1")
    hierarchy = (queue.annotations or {}).get(HIERARCHY_ANNOTATION)
    weights = (queue.annotations or {}).get(HIERARCHY_WEIGHT_ANNOTATION)
    if hierarchy or weights:
        if not (hierarchy and weights):
            raise AdmissionError(
                "both hierarchy and hierarchy-weights must be set")
        paths = hierarchy.split("/")
        wparts = weights.split("/")
        if len(paths) != len(wparts):
            raise AdmissionError(
                f"hierarchy {hierarchy} and weights {weights} must have "
                f"the same depth")
        for w in wparts:
            try:
                if float(w) <= 0:
                    raise ValueError
            except ValueError:
                raise AdmissionError(
                    f"hierarchy weight {w!r} must be a positive number")
        if paths[0] != "root":
            raise AdmissionError("hierarchy must start from 'root'")
        # a queue's path must not be a prefix of another queue's path
        for other in cluster.list("queues"):
            if other.name == queue.name:
                continue
            oh = (other.annotations or {}).get(HIERARCHY_ANNOTATION)
            if not oh:
                continue
            if oh.startswith(hierarchy + "/") or hierarchy.startswith(oh + "/"):
                raise AdmissionError(
                    f"hierarchy {hierarchy} conflicts with queue "
                    f"{other.name}'s hierarchy {oh}")
    return queue


def mutate_queue(verb: str, queue: Queue, cluster,
                 opts=None) -> Queue:
    if verb != "create":
        return queue
    if not queue.spec.weight:
        queue.spec.weight = 1
    if queue.spec.reclaimable is None:
        queue.spec.reclaimable = True
    return queue


def register() -> None:
    register_admission_service(AdmissionService(
        path="/queues/mutate", kind="queues", verbs=["create"],
        func=mutate_queue))
    register_admission_service(AdmissionService(
        path="/queues/validate", kind="queues", verbs=["create", "delete"],
        func=validate_queue))
