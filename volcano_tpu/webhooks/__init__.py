"""Admission webhooks (reference pkg/webhooks)."""

from . import jobs, pods, queues  # noqa: F401
from .router import (  # noqa: F401
    AdmissionService, WebhookManager, list_services,
    register_admission_service,
)

_registered = False


def register_all() -> None:
    global _registered
    if _registered:
        return
    jobs.register()
    pods.register()
    queues.register()
    _registered = True


def start_webhooks(cluster, scheduler_name: str = "volcano",
                   default_queue: str = "default") -> WebhookManager:
    """Register all admission services and bind them to the store."""
    register_all()
    wm = WebhookManager(cluster, scheduler_name,
                        default_queue=default_queue)
    wm.run()
    return wm


def serve_webhooks(cluster, host: str = "127.0.0.1", port: int = 0,
                   cert_path=None, key_path=None, client_ca_path=None,
                   scheduler_name: str = "volcano",
                   default_queue: str = "default"):
    """Register all admission services and serve them over TLS (the
    reference's webhook-manager deployment shape). Returns the server;
    call .start_background() or .serve_forever(). Pass client_ca_path to
    require mutual TLS — any non-loopback deployment should (the k8s
    manifest wires it)."""
    from .router import AdmissionOptions
    from .server import AdmissionServer

    register_all()
    return AdmissionServer(cluster, host=host, port=port,
                           cert_path=cert_path, key_path=key_path,
                           client_ca_path=client_ca_path,
                           opts=AdmissionOptions(
                               scheduler_name=scheduler_name,
                               default_queue=default_queue))
