"""Admission router (reference pkg/webhooks/router/admission.go:30).

AdmissionServices register (kind, verbs, func); the WebhookManager adapts
them onto the ClusterStore's interceptor chain — the in-process equivalent
of the reference's HTTPS ValidatingWebhookConfiguration path. A real
deployment would serve the same handlers over TLS.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List

from ..client.store import AdmissionError, ClusterStore


@dataclass
class AdmissionOptions:
    """Per-control-plane admission config (the webhook-manager binary's
    flags): instance state, NOT module globals, so multiple control
    planes in one process can't clobber each other."""
    scheduler_name: str = "volcano"
    default_queue: str = "default"


@dataclass
class AdmissionService:
    path: str
    kind: str                     # store bucket name, e.g. "jobs"
    verbs: List[str]              # subset of {create, update, delete}
    func: Callable                # (verb, obj, store, opts) -> obj (raise AdmissionError to deny)


_services: List[AdmissionService] = []


def register_admission_service(svc: AdmissionService) -> None:
    _services.append(svc)


def list_services() -> List[AdmissionService]:
    return list(_services)


class WebhookManager:
    """cmd/webhook-manager equivalent: binds every registered admission
    service to a cluster store."""

    def __init__(self, cluster: ClusterStore, scheduler_name: str = "volcano",
                 default_queue: str = "default"):
        self.cluster = cluster
        self.scheduler_name = scheduler_name
        self.opts = AdmissionOptions(scheduler_name=scheduler_name,
                                     default_queue=default_queue)

    def run(self) -> None:
        cluster = self.cluster
        opts = self.opts

        def interceptor(verb: str, kind: str, obj):
            for svc in _services:
                if svc.kind == kind and verb in svc.verbs:
                    obj = svc.func(verb, obj, cluster, opts)
            return obj

        cluster.add_interceptor(interceptor)
