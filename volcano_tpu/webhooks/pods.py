"""Pod admission gate (reference webhooks/admission/pods/admit_pod.go:39-130).

Pods belonging to a PodGroup that has not reached Inqueue are rejected:
this delays pod creation until the scheduler admits the gang, keeping
cluster pressure proportional to admitted work.
"""

from __future__ import annotations

from ..api.types import POD_GROUP_ANNOTATION
from ..client.store import AdmissionError
from ..models import Pod, PodGroupPhase
from .router import AdmissionService, register_admission_service


def validate_pod(verb: str, pod: Pod, cluster,
                 opts=None) -> Pod:
    if verb != "create":
        return pod
    # scope to the CONFIGURED scheduler name (admit_pod.go checks the
    # configured scheduler-names list): under --scheduler-name the gate
    # must follow the renamed control plane, not the literal default
    scheduler_name = opts.scheduler_name if opts is not None else "volcano"
    if pod.scheduler_name != scheduler_name:
        return pod
    pg_name = (pod.annotations or {}).get(POD_GROUP_ANNOTATION)
    if not pg_name:
        return pod  # bare pod: podgroup controller will wrap it
    pg = cluster.try_get("podgroups", pg_name, pod.namespace)
    if pg is None:
        return pod  # group not created yet; controller orders creation
    if pg.status.phase == PodGroupPhase.PENDING:
        raise AdmissionError(
            f"failed to create pod <{pod.namespace}/{pod.name}>, "
            f"because the podgroup phase is Pending")
    return pod


def register() -> None:
    register_admission_service(AdmissionService(
        path="/pods/validate", kind="pods", verbs=["create"],
        func=validate_pod))
