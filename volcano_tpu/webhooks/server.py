"""HTTPS admission boundary (reference cmd/webhook-manager/app/
{server.go:37-98, certificate.go}).

The in-process interceptor chain (router.WebhookManager) is the test seam;
this module is the served network boundary the reference deploys: a TLS
server exposing every registered AdmissionService at its path, speaking an
AdmissionReview-shaped JSON protocol:

    request:  {"request": {"operation": "CREATE"|"UPDATE"|"DELETE",
                           "kind": "<store bucket>", "object": {...}}}
    response: {"response": {"allowed": bool, "status": {"message": str},
                            "object": {...}}}   # object = mutated result

Certificates are generated self-signed at startup when not supplied
(certificate.go does the same CA bootstrap); objects cross the wire as
plain JSON and are rebuilt into the typed models via the dataclass codec
below (the reference gets this from k8s codegen).
"""

from __future__ import annotations

import dataclasses
import http.server
import json
import ssl
import threading
import typing
from typing import Optional, Tuple

from ..client.store import AdmissionError
from .router import list_services


# -- dataclass <-> dict codec ------------------------------------------------

def to_wire(obj):
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: to_wire(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    if isinstance(obj, bytes):
        return obj.decode("latin1")
    if hasattr(obj, "value") and obj.__class__.__module__.endswith(
            ("scheduling", "bus", "batch")):
        return obj.value  # enums
    return obj


def _resolve(tp):
    origin = typing.get_origin(tp)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _resolve(args[0]) if len(args) == 1 else (None, None)
    return tp, origin


def from_wire(tp, data):
    """Best-effort reconstruction of a (possibly nested) dataclass from
    plain JSON; unknown keys are dropped, enums coerced by value."""
    tp, origin = _resolve(tp)
    if data is None or tp is None:
        return data
    if dataclasses.is_dataclass(tp):
        if not isinstance(data, dict):
            return data
        hints = typing.get_type_hints(tp)
        kwargs = {}
        for f in dataclasses.fields(tp):
            if f.name in data:
                kwargs[f.name] = from_wire(hints.get(f.name), data[f.name])
        return tp(**kwargs)
    if isinstance(tp, type) and issubclass(tp, __import__("enum").Enum):
        try:
            return tp(data)
        except ValueError:
            return data
    if origin in (list, tuple):
        (item_tp,) = typing.get_args(tp) or (None,)
        return [from_wire(item_tp, v) for v in data]
    if origin is dict:
        return data
    return data


#: wire kind -> model class (the store bucket names admission services use)
def _model_for(kind: str):
    from .. import models

    return {
        "jobs": models.Job,
        "pods": models.Pod,
        "queues": models.Queue,
        "podgroups": models.PodGroup,
        "commands": models.Command,
    }.get(kind)


# -- self-signed certificates (certificate.go) -------------------------------

def generate_self_signed_cert(cert_dir: Optional[str] = None,
                              common_name: str = "volcano-webhook"
                              ) -> Tuple[str, str]:
    """Write key.pem/cert.pem under cert_dir (a fresh private tmpdir when
    None); returns their paths. The key file is owner-readable only."""
    import datetime
    import os
    import tempfile

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost"), x509.DNSName(common_name)]),
                critical=False)
            .sign(key, hashes.SHA256()))
    if cert_dir is None:
        cert_dir = tempfile.mkdtemp(prefix="volcano-webhook-certs-")
    else:
        os.makedirs(cert_dir, mode=0o700, exist_ok=True)
        os.chmod(cert_dir, 0o700)
    key_path = os.path.join(cert_dir, "key.pem")
    cert_path = os.path.join(cert_dir, "cert.pem")
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    return cert_path, key_path


# -- the served boundary -----------------------------------------------------

class AdmissionServer:
    """TLS admission server over the registered AdmissionServices.

    Client authentication: pass ``client_ca_path`` to require mutual TLS
    (the reference's webhook is authenticated by the API server; a bare
    deployment of this one would otherwise accept admission traffic from
    anyone who can reach the port — ADVICE r2 #5). The default, no client
    verification, is for dev/loopback use only — the default bind address
    stays 127.0.0.1 for that reason.
    """

    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 0,
                 cert_path: Optional[str] = None,
                 key_path: Optional[str] = None,
                 cert_dir: Optional[str] = None,
                 client_ca_path: Optional[str] = None,
                 opts=None):
        from .router import AdmissionOptions

        if cert_path is None or key_path is None:
            cert_path, key_path = generate_self_signed_cert(cert_dir)
        self.cert_path = cert_path
        self.cluster = cluster
        opts = opts or AdmissionOptions()
        services = {svc.path: svc for svc in list_services()}

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                svc = services.get(self.path)
                if svc is None:
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    review = json.loads(self.rfile.read(length))
                    req = review.get("request") or {}
                    verb = (req.get("operation") or "CREATE").lower()
                    model = _model_for(svc.kind)
                    obj = from_wire(model, req.get("object"))
                    if verb in svc.verbs:
                        out = svc.func(verb, obj, cluster, opts)
                    else:
                        # verbs the service didn't register for pass
                        # through unchanged, like the interceptor chain
                        out = obj
                    body = {"response": {"allowed": True,
                                         "object": to_wire(out)}}
                except AdmissionError as e:
                    body = {"response": {"allowed": False,
                                         "status": {"message": str(e)}}}
                except Exception as e:  # noqa: BLE001 — malformed review
                    body = {"response": {"allowed": False,
                                         "status": {"message":
                                                    f"bad request: {e}"}}}
                raw = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert_path, key_path)
        if client_ca_path is not None:
            # mutual TLS: only clients presenting a cert signed by this
            # CA may drive admission
            ctx.load_verify_locations(cafile=client_ca_path)
            ctx.verify_mode = ssl.CERT_REQUIRED
        self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                             server_side=True)
        self.address = self._httpd.server_address

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def start_background(self) -> threading.Thread:
        th = threading.Thread(target=self.serve_forever, daemon=True)
        th.start()
        return th

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
