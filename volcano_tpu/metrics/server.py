"""HTTP exposition: /metrics, /healthz, /debug/stacks.

The reference serves promhttp plus net/http/pprof on --listen-address
(cmd/scheduler/app/server.go:76-77, cmd/scheduler/main.go:25). The Python
equivalent of the pprof goroutine dump is a live thread-stack dump.
"""

from __future__ import annotations

import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .metrics import registry as default_registry

DEFAULT_LISTEN_PORT = 8080


def _dump_stacks() -> str:
    import sys
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


class MetricsServer:
    """Serves the metric registry on a daemon thread."""

    def __init__(self, port: int = DEFAULT_LISTEN_PORT, registry=None,
                 host: str = "127.0.0.1"):
        self.registry = registry or default_registry
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/metrics":
                    body = outer.registry.expose().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                elif self.path == "/debug/stacks":
                    body, ctype = _dump_stacks().encode(), "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence request logging
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]  # resolved if port=0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-server",
            daemon=True)

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
