"""Metrics (reference pkg/scheduler/metrics)."""

from . import metrics  # noqa: F401
from .metrics import Counter, Gauge, Histogram, Registry, registry  # noqa: F401
from .server import DEFAULT_LISTEN_PORT, MetricsServer  # noqa: F401
