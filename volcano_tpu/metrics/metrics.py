"""Prometheus-style metrics registry (reference pkg/scheduler/metrics/).

A dependency-free implementation of counters/gauges/histograms with labels
and text exposition, covering the reference's metric set
(metrics.go:41-128, queue.go, job.go, namespace.go).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

VOLCANO_NAMESPACE = "volcano"


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


#: one lock for all metric mutations: observations come from the scheduler
#: thread, the async effector pool, and the job-updater fan-out; the
#: read-modify-write ops below are not atomic under the GIL
_metrics_lock = threading.Lock()


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Iterable[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = list(label_names)


class Counter(_Metric):
    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple, float] = {}

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None):
        k = _label_key(labels)
        with _metrics_lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Gauge(_Metric):
    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, labels: Optional[Dict[str, str]] = None):
        self._values[_label_key(labels)] = value

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def delete(self, labels: Optional[Dict[str, str]] = None):
        self._values.pop(_label_key(labels), None)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


_DEF_BUCKETS = tuple(0.001 * (2 ** i) for i in range(15))  # 1ms .. ~16s


class Histogram(_Metric):
    def __init__(self, name, help_, label_names=(), buckets=_DEF_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple, List[int]] = {}
        self._sum: Dict[Tuple, float] = {}
        self._n: Dict[Tuple, int] = {}

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None):
        k = _label_key(labels)
        with _metrics_lock:
            self._observe_locked(k, value)

    def _observe_locked(self, k, value: float):
        # per-BUCKET tallies with one bisect (cumulative sums are computed
        # at collect time): observe() runs once per bind on the replay hot
        # path, where the previous 15-increment linear scan was measurable
        # at 10k tasks/cycle
        counts = self._counts.setdefault(k, [0] * len(self.buckets))
        i = bisect.bisect_left(self.buckets, value)
        if i < len(self.buckets):
            counts[i] += 1
        self._sum[k] = self._sum.get(k, 0.0) + value
        self._n[k] = self._n.get(k, 0) + 1

    def observe_many(self, values, labels: Optional[Dict[str, str]] = None):
        """Batch observe: one lock/key resolution for a whole wave of
        samples (the batched bind effector observes per task; a 10k-pod
        burst is 10k samples)."""
        values = list(values)
        if not values:
            return
        k = _label_key(labels)
        with _metrics_lock:
            counts = self._counts.setdefault(k, [0] * len(self.buckets))
            total = 0.0
            nb = len(self.buckets)
            for value in values:
                i = bisect.bisect_left(self.buckets, value)
                if i < nb:
                    counts[i] += 1
                total += value
            self._sum[k] = self._sum.get(k, 0.0) + total
            self._n[k] = self._n.get(k, 0) + len(values)

    def get_count(self, labels=None) -> int:
        return self._n.get(_label_key(labels), 0)

    def get_sum(self, labels=None) -> float:
        return self._sum.get(_label_key(labels), 0.0)

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for k in sorted(self._n):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[k][i]
                lk = k + (("le", repr(b)),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} {cum}")
            out.append(f"{self.name}_bucket{_fmt_labels(k + (('le', '+Inf'),))} {self._n[k]}")
            out.append(f"{self.name}_sum{_fmt_labels(k)} {self._sum[k]}")
            out.append(f"{self.name}_count{_fmt_labels(k)} {self._n[k]}")
        return out


def _fmt_labels(k: Tuple) -> str:
    if not k:
        return ""
    inner = ",".join(f'{name}="{val}"' for name, val in k)
    return "{" + inner + "}"


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []

    def register(self, m):
        self._metrics.append(m)
        return m

    def expose(self) -> str:
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"


registry = Registry()

# -- scheduler metrics (metrics.go:41-128) ----------------------------------

e2e_scheduling_latency = registry.register(Histogram(
    "volcano_e2e_scheduling_latency_milliseconds",
    "E2e scheduling latency in milliseconds"))
action_scheduling_latency = registry.register(Histogram(
    "volcano_action_scheduling_latency_microseconds",
    "Action scheduling latency", ["action"]))
plugin_scheduling_latency = registry.register(Histogram(
    "volcano_plugin_scheduling_latency_microseconds",
    "Plugin scheduling latency", ["plugin", "OnSession"]))
task_scheduling_latency = registry.register(Histogram(
    "volcano_task_scheduling_latency_milliseconds",
    "Task scheduling latency"))
schedule_attempts = registry.register(Counter(
    "volcano_schedule_attempts_total",
    "Number of attempts to schedule pods, by the result", ["result"]))
pod_schedule_errors = registry.register(Counter(
    "volcano_pod_schedule_errors", "Pods that failed to schedule"))
pod_schedule_successes = registry.register(Counter(
    "volcano_pod_schedule_successes", "Pods that scheduled"))
preemption_victims = registry.register(Gauge(
    "volcano_preemption_victims", "Number of selected preemption victims"))
preemption_attempts = registry.register(Counter(
    "volcano_total_preemption_attempts",
    "Total preemption attempts in the cluster"))
unschedule_task_count = registry.register(Gauge(
    "volcano_unschedule_task_count", "Unschedulable task count", ["job_id"]))
unschedule_job_count = registry.register(Gauge(
    "volcano_unschedule_job_count", "Unschedulable job count"))

# -- queue metrics (queue.go) ----------------------------------------------

queue_allocated_milli_cpu = registry.register(Gauge(
    "volcano_queue_allocated_milli_cpu", "Allocated CPU by queue", ["queue_name"]))
queue_allocated_memory_bytes = registry.register(Gauge(
    "volcano_queue_allocated_memory_bytes", "Allocated memory by queue", ["queue_name"]))
queue_request_milli_cpu = registry.register(Gauge(
    "volcano_queue_request_milli_cpu", "Requested CPU by queue", ["queue_name"]))
queue_request_memory_bytes = registry.register(Gauge(
    "volcano_queue_request_memory_bytes", "Requested memory by queue", ["queue_name"]))
queue_deserved_milli_cpu = registry.register(Gauge(
    "volcano_queue_deserved_milli_cpu", "Deserved CPU by queue", ["queue_name"]))
queue_deserved_memory_bytes = registry.register(Gauge(
    "volcano_queue_deserved_memory_bytes", "Deserved memory by queue", ["queue_name"]))
queue_share = registry.register(Gauge(
    "volcano_queue_share", "Share of queue", ["queue_name"]))
queue_weight = registry.register(Gauge(
    "volcano_queue_weight", "Weight of queue", ["queue_name"]))
queue_overused = registry.register(Gauge(
    "volcano_queue_overused", "Whether queue is overused", ["queue_name"]))
queue_pod_group_inqueue_count = registry.register(Gauge(
    "volcano_queue_pod_group_inqueue_count", "Inqueue PodGroup count", ["queue_name"]))
queue_pod_group_pending_count = registry.register(Gauge(
    "volcano_queue_pod_group_pending_count", "Pending PodGroup count", ["queue_name"]))
queue_pod_group_running_count = registry.register(Gauge(
    "volcano_queue_pod_group_running_count", "Running PodGroup count", ["queue_name"]))
queue_pod_group_unknown_count = registry.register(Gauge(
    "volcano_queue_pod_group_unknown_count", "Unknown PodGroup count", ["queue_name"]))

# -- compile/dispatch pipeline metrics (ops.precompile) ---------------------

solver_compile_total = registry.register(Counter(
    "volcano_solver_compile_total",
    "XLA backend compiles, by observing thread class", ["thread"]))
solver_compile_seconds_total = registry.register(Counter(
    "volcano_solver_compile_seconds_total",
    "Seconds spent in XLA backend compiles, by thread class", ["thread"]))
compile_cache_hits_total = registry.register(Counter(
    "volcano_compile_cache_hits_total",
    "Persistent compilation cache hits"))
prewarm_completions_total = registry.register(Counter(
    "volcano_prewarm_completions_total",
    "Background bucket pre-warm completions"))
session_phase_ms = registry.register(Gauge(
    "volcano_session_phase_milliseconds",
    "Per-phase latency of the last scheduling cycle", ["phase"]))

# -- device-resident arena metrics (ops.device_cache + ops.pipeline) --------

arena_bytes_shipped = registry.register(Gauge(
    "volcano_arena_bytes_shipped",
    "Wire bytes shipped to the device-resident arena by the last "
    "scheduling session (dirty chunks only in steady state), per solver "
    "mode (packed = single-device arena, sharded = node-axis mesh arena)",
    ["mode"]))
arena_bytes_shipped_total = registry.register(Gauge(
    "volcano_arena_bytes_shipped_total",
    "Cumulative wire bytes shipped to the device-resident arena, per "
    "solver mode", ["mode"]))
arena_hit_rate = registry.register(Gauge(
    "volcano_arena_hit_rate",
    "Fraction of sessions served by a delta against the resident arena "
    "(1.0 = no full re-ship since the first session), per solver mode",
    ["mode"]))
arena_sessions_total = registry.register(Gauge(
    "volcano_arena_sessions_total",
    "Arena sessions by outcome (delta = dirty-chunk ship, full = "
    "full padded-buffer upload) and solver mode", ["outcome", "mode"]))
arena_invalidations_total = registry.register(Gauge(
    "volcano_arena_invalidations_total",
    "Soft arena invalidations after collect failures (next session "
    "full-ships and re-validates pinned params), per solver mode",
    ["mode"]))
arena_params_repins_total = registry.register(Gauge(
    "volcano_arena_params_repins_total",
    "Device score-params uploads (content change or failed "
    "re-validation; steady sessions serve the pinned copy), per solver "
    "mode", ["mode"]))
arena_shard_bytes_shipped = registry.register(Gauge(
    "volcano_arena_shard_bytes_shipped",
    "Wire bytes shipped to one mesh shard by the last sharded session "
    "(node-axis dirty chunks owned by the shard + its copy of the "
    "replicated task/job delta)", ["shard"]))

# -- event-sourced flatten metrics (ops.arrays FlattenCache ledger) ---------

flatten_cycles_total = registry.register(Counter(
    "volcano_flatten_cycles_total",
    "Scheduling-cycle flattens by assembly mode: event = ledger-driven "
    "row patch (O(events)), incremental = prefix/suffix re-diff, cold = "
    "full rebuild", ["mode"]))
flatten_events_applied = registry.register(Gauge(
    "volcano_flatten_events_applied",
    "Mirror deltas consumed by the last flatten's event ledger (watch "
    "deliveries + snapshot-seam re-cuts since the previous flatten)"))
flatten_rows_patched = registry.register(Gauge(
    "volcano_flatten_rows_patched",
    "Padded buffer rows (task rows + node rows) patched in place by the "
    "last event-mode flatten; 0 on a quiet cluster"))
flatten_rows_patched_total = registry.register(Counter(
    "volcano_flatten_rows_patched_total",
    "Cumulative rows patched by event-mode flattens"))
flatten_patch_ms = registry.register(Gauge(
    "volcano_flatten_patch_milliseconds",
    "Wall time of the last EVENT-mode flatten (validate epoch, patch "
    "dirty rows, reuse the assembly)"))
flatten_full_ms = registry.register(Gauge(
    "volcano_flatten_full_milliseconds",
    "Wall time of the last full-pass flatten (incremental re-diff or "
    "cold rebuild)"))
flatten_fallbacks_total = registry.register(Counter(
    "volcano_flatten_fallbacks_total",
    "Event-path declines into the full re-diff, by reason (epoch_"
    "mismatch, node_relayout, job_layout, task_count, vocab_growth, "
    "session_mutations, ...)", ["reason"]))

# -- event-sourced ordering metrics (ops.ordering OrderCache) ---------------

order_cycles_total = registry.register(Counter(
    "volcano_order_cycles_total",
    "Scheduling-cycle ordering passes by mode: reuse = quiet-cycle walk "
    "reuse (zero work), event = ledger-driven patch of dirty jobs only, "
    "full = full keyed re-sort, legacy = comparator-only conf (cache "
    "stands down)", ["mode"]))
order_entries_patched = registry.register(Gauge(
    "volcano_order_entries_patched",
    "Jobs re-filtered/re-keyed/re-sorted by the last ordering pass; 0 on "
    "a quiet cluster, the full job count on a fallback cycle"))
order_entries_patched_total = registry.register(Counter(
    "volcano_order_entries_patched_total",
    "Cumulative job entries patched by event-mode ordering passes"))
order_ms = registry.register(Gauge(
    "volcano_order_milliseconds",
    "Wall time of the last EVENT-path ordering pass (reuse or "
    "dirty-entry patch + index walk)"))
order_full_ms = registry.register(Gauge(
    "volcano_order_full_milliseconds",
    "Wall time of the last full-sort ordering pass (fallback or "
    "comparator-only collection)"))
order_fallbacks_total = registry.register(Counter(
    "volcano_order_fallbacks_total",
    "Event-path ordering declines into the full sort, by reason (epoch_"
    "mismatch, conf_reload, key_context, session_mutations, queue_"
    "membership, comparator_only, ...)", ["reason"]))

# -- delta watch metrics (client/codec.py delta dialect, client/remote.py) --

delta_frames_total = registry.register(Counter(
    "volcano_delta_frames_total",
    "Wire frames received on negotiated delta watch streams (patch and "
    "interleaved object frames alike)"))
delta_patches_applied_total = registry.register(Counter(
    "volcano_delta_patches_applied_total",
    "Column-patch events applied straight onto mirrored objects (no "
    "full-object decode)"))
delta_fields_applied_total = registry.register(Counter(
    "volcano_delta_fields_applied_total",
    "Individual field writes applied by column patches"))
delta_stream_bytes_total = registry.register(Counter(
    "volcano_delta_stream_bytes_total",
    "Watch-stream wire bytes by mode: delta = frames on a negotiated "
    "delta stream, object = plain object frames — the like-for-like "
    "bytes comparison between the two paths", ["mode"]))
delta_decode_ms = registry.register(Gauge(
    "volcano_delta_decode_milliseconds",
    "Cumulative wall time resolving patch columns (table lookups + raw-"
    "value decodes) on this client's delta streams"))
delta_apply_ms = registry.register(Gauge(
    "volcano_delta_apply_milliseconds",
    "Cumulative wall time applying resolved patches (field writes + "
    "listener dispatch) on this client's delta streams"))
delta_vocab_size = registry.register(Gauge(
    "volcano_delta_vocab_size",
    "Peak interning-table size across this client's delta streams "
    "(capped at codec.DELTA_VOCAB_MAX; overflow falls back typed)"))
delta_fallbacks_total = registry.register(Counter(
    "volcano_delta_fallbacks_total",
    "Typed delta-stream fallbacks to the object path, by reason (delta_"
    "gap, vocab_overflow, unknown_field, schema_skew)", ["reason"]))

# -- resilience metrics (resilience/, scheduler containment, store client) --

breaker_state = registry.register(Gauge(
    "volcano_breaker_state",
    "Circuit breaker state (0=closed, 1=half_open, 2=open)", ["breaker"]))
breaker_transitions_total = registry.register(Counter(
    "volcano_breaker_transitions_total",
    "Circuit breaker state transitions", ["breaker", "to"]))
breaker_fallback_cycles_total = registry.register(Counter(
    "volcano_breaker_fallback_cycles_total",
    "Scheduling cycles served by the host oracle while the device "
    "breaker was not closed", ["breaker"]))
conf_load_errors = registry.register(Counter(
    "volcano_conf_load_errors",
    "Scheduler conf hot-reload failures (last good conf retained)"))
action_failures_total = registry.register(Counter(
    "volcano_action_failures_total",
    "Scheduling actions contained after raising", ["action"]))
action_timeouts_total = registry.register(Counter(
    "volcano_action_timeouts_total",
    "Scheduling actions contained after a deadline breach", ["action"]))
watch_reconnects_total = registry.register(Counter(
    "volcano_watch_reconnects_total",
    "Watch streams resumed in place after a break", ["kind"]))
store_request_retries_total = registry.register(Counter(
    "volcano_store_request_retries_total",
    "Store client requests retried after a connection failure"))
faults_injected_total = registry.register(Counter(
    "volcano_faults_injected_total",
    "Faults fired by the injection harness", ["point"]))
fenced_writes_total = registry.register(Counter(
    "volcano_fenced_writes_total",
    "Mutating store writes rejected by lease fencing (split-brain "
    "attempts made visible)", ["holder"]))
bind_intents_total = registry.register(Counter(
    "volcano_bind_intents_total",
    "Bind-intent journal activity (recorded / confirmed)", ["event"]))
recovery_intents_total = registry.register(Counter(
    "volcano_recovery_intents_total",
    "Bind-intent bindings reconciled at leadership takeover, by outcome "
    "(adopted / redriven / conflict / lost)", ["outcome"]))
job_retry_total = registry.register(Counter(
    "volcano_job_retry_total",
    "Job controller re-enqueues after a failed sync (capped exponential "
    "backoff per job key)", ["job_id"]))

# -- store admission metrics (resilience/overload.py AdmissionGate) ---------
# every request-serving surface (StoreServer, ShardRouter, shard
# workers, ProcShardRouter, ReplicaServer) exports these through its
# process's registry; the retry-budget pair is CLIENT-side
# (RemoteClusterStore's token bucket)

store_admission_inflight = registry.register(Gauge(
    "volcano_store_admission_inflight",
    "Requests (and held streams) currently dispatched per admission "
    "lane; system is unbounded, the bounded lanes queue then shed",
    ["lane"]))
store_admission_queued = registry.register(Gauge(
    "volcano_store_admission_queued",
    "Requests waiting in one admission lane's bounded FIFO (granted "
    "round-robin across client flows; shed typed when the queue fills "
    "or the queue-wait deadline passes)", ["lane"]))
store_admission_sheds_total = registry.register(Counter(
    "volcano_store_admission_sheds_total",
    "Requests shed at the admission gate, by lane and reason "
    "(queue_full, queue_wait, deadline, streams, fault). Every shed is "
    "a typed OverloadedError with a retry-after hint — never a hang, "
    "never a silent drop", ["lane", "reason"]))
store_admission_deadline_expired_total = registry.register(Counter(
    "volcano_store_admission_deadline_expired_total",
    "Requests rejected because their wire deadline (deadline_ms "
    "header) had already expired on arrival or lapsed while queued — "
    "work nobody is waiting for anymore, not worth a thread", ["lane"]))
store_admission_retry_budget = registry.register(Gauge(
    "volcano_store_admission_retry_budget",
    "Client-side retry-budget token balance (refilled at ~10% of "
    "recent request volume; each Overloaded retry spends one)"))
store_admission_retry_budget_exhausted_total = registry.register(Counter(
    "volcano_store_admission_retry_budget_exhausted_total",
    "Overloaded retries refused client-side because the retry budget "
    "was dry (typed RetryBudgetExhausted to the caller; system-lane "
    "ops bypass the budget)"))

# -- durable store metrics (client/durable.py + client/server.py) -----------

store_watch_dropped_total = registry.register(Counter(
    "volcano_store_watch_dropped_total",
    "Slow watchers dropped by the store server (event queue overflow or "
    "send stall past the timeout); the client resumes via its rv "
    "high-water mark"))
store_wal_appends_total = registry.register(Counter(
    "volcano_store_wal_appends_total",
    "Mutation records appended to the store write-ahead log"))
store_wal_append_seconds = registry.register(Histogram(
    "volcano_store_wal_append_seconds",
    "Latency of one WAL append (encode + write + policy fsync)"))
store_wal_fsyncs_total = registry.register(Counter(
    "volcano_store_wal_fsyncs_total",
    "WAL fsyncs (every commit under fsync=every, one per bulk_apply "
    "batch, at most one per interval under fsync=interval)"))
store_wal_size_bytes = registry.register(Gauge(
    "volcano_store_wal_size_bytes",
    "Bytes in the active WAL segment (resets at every snapshot "
    "rotation)"))
store_wal_snapshots_total = registry.register(Counter(
    "volcano_store_wal_snapshots_total",
    "Store snapshots written (WAL compactions)"))
store_wal_snapshot_bytes = registry.register(Gauge(
    "volcano_store_wal_snapshot_bytes",
    "Size of the newest store snapshot"))
store_wal_snapshot_timestamp = registry.register(Gauge(
    "volcano_store_wal_snapshot_timestamp_seconds",
    "Unix time the newest store snapshot was written (snapshot age = "
    "now - this)"))
store_wal_recovery_ms = registry.register(Gauge(
    "volcano_store_wal_recovery_milliseconds",
    "Wall time of the last store recovery (snapshot load + WAL tail "
    "replay)"))
store_wal_recovery_records = registry.register(Gauge(
    "volcano_store_wal_recovery_records",
    "WAL records replayed on top of the snapshot by the last recovery"))

# -- sharded store metrics (client/sharded.py) ------------------------------
# the volcano_store_wal_* family above additionally carries a
# shard=<idx> label when the WAL belongs to a sharded member store

store_shard_events_total = registry.register(Counter(
    "volcano_store_shard_events_total",
    "Events committed per store shard (rate = per-shard events/sec)",
    ["shard"]))
store_shard_journal_window = registry.register(Gauge(
    "volcano_store_shard_journal_window",
    "Events currently replayable from one shard's watch-resume journal "
    "(the span of its since: window, sampled every 64 commits)",
    ["shard"]))
store_shard_watch_queue_depth = registry.register(Gauge(
    "volcano_store_shard_watch_queue_depth",
    "Events from one shard sitting in router watch queues, not yet on "
    "the wire (sustained growth = a slow watcher about to be dropped)",
    ["shard"]))
store_shard_dropped_total = registry.register(Counter(
    "volcano_store_shard_dropped_events_total",
    "Events discarded per shard when a condemned (overflowed/stalled) "
    "watch stream was dropped", ["shard"]))

# -- multi-process shard workers (client/shardproc.py) ----------------------
# set by the ShardProcSupervisor in the router process

store_shard_worker_up = registry.register(Gauge(
    "volcano_store_shard_worker_up",
    "1 when the shard's worker process is alive and serving, 0 while "
    "it is down/restarting (its ops contained with "
    "ShardUnavailableError)", ["shard"]))
store_shard_worker_pid = registry.register(Gauge(
    "volcano_store_shard_worker_pid",
    "OS pid of the shard's worker process", ["shard"]))
store_shard_worker_restarts_total = registry.register(Counter(
    "volcano_store_shard_worker_restarts_total",
    "Times the supervisor restarted this shard's worker process "
    "(capped-exponential-backoff respawn on the same port + data dir)",
    ["shard"]))
store_shard_worker_uptime_seconds = registry.register(Gauge(
    "volcano_store_shard_worker_uptime_seconds",
    "Seconds since the shard's worker process last came READY "
    "(0 while down)", ["shard"]))
store_shard_ingest_events_per_sec = registry.register(Gauge(
    "volcano_store_shard_ingest_events_per_sec",
    "Committed mutations per second on this shard's worker, sampled "
    "from its rv progression by the supervisor's liveness polls",
    ["shard"]))

# -- read replica metrics (client/replica.py) -------------------------------

replica_applied_rv = registry.register(Gauge(
    "volcano_replica_applied_rv",
    "Primary resource_version this replica's mirror reflects, per "
    "shipped WAL lineage (shard '0' for an unsharded primary)",
    ["shard"]))
replica_lag_records = registry.register(Gauge(
    "volcano_replica_lag_records",
    "WAL records the primary has committed that this replica has not "
    "yet applied (primary rv seen on the ship stream - applied rv)",
    ["shard"]))
replica_lag_seconds = registry.register(Gauge(
    "volcano_replica_lag_seconds",
    "Age of the replica's applied state while it lags (now - the WAL "
    "commit stamp of the last applied record; 0 when caught up)",
    ["shard"]))
replica_bootstraps_total = registry.register(Counter(
    "volcano_replica_bootstraps_total",
    "Replica snapshot bootstraps by reason: initial (startup), "
    "out_of_window (fell past the primary's retained-segment window), "
    "apply_gap (rv discontinuity detected — a lost or duplicated "
    "shipped record). Every hole ends here, never in a silent skip",
    ["reason"]))
replica_ship_bytes_total = registry.register(Counter(
    "volcano_replica_ship_bytes_total",
    "Wire bytes received on the WAL ship stream(s)", ["shard"]))
replica_watchers = registry.register(Gauge(
    "volcano_replica_watchers",
    "Watch/bulk_watch streams currently served by this replica"))
replica_upstream_depth = registry.register(Gauge(
    "volcano_replica_upstream_depth",
    "This replica's depth in the fan-out tree: 1 tails the primary "
    "directly, N tails a depth-(N-1) replica"))
replica_upstream_rv = registry.register(Gauge(
    "volcano_replica_upstream_rv",
    "Newest upstream resource_version seen on this replica's ship "
    "stream(s), per lineage — the rv its lag is measured against",
    ["shard"]))
replica_ship_served_streams = registry.register(Gauge(
    "volcano_replica_ship_served_streams",
    "Downstream ship streams this replica is currently re-serving "
    "(its children in the fan-out tree)"))
replica_ship_served_records_total = registry.register(Counter(
    "volcano_replica_ship_served_records_total",
    "WAL records this replica relayed to downstream replicas — "
    "traffic the primary never saw"))
replica_ship_served_bootstraps_total = registry.register(Counter(
    "volcano_replica_ship_served_bootstraps_total",
    "Bootstrap requests this replica answered from its own mirror "
    "state (mid-tree re-bootstraps that never touched the primary)"))

# -- global rescheduler metrics (reschedule/) -------------------------------

reschedule_plans_total = registry.register(Counter(
    "volcano_reschedule_plans_total",
    "Defragmentation plans by outcome: executed, pre-solve skips "
    "(empty / fits / no_hole / skipped_breaker / solve_failed) and "
    "post-solve plan rejections (rejected_no_gain / rejected_no_hole / "
    "rejected_fits / rejected_empty / rejected_budget)", ["outcome"]))
reschedule_moves_total = registry.register(Counter(
    "volcano_reschedule_moves_total",
    "Migration moves by stage (proposed = raw solved-vs-incumbent diff, "
    "selected = survived budget/caps/feasibility, executed = evictions "
    "dispatched, capped = cut by bounding)", ["stage"]))
reschedule_fragmentation = registry.register(Gauge(
    "volcano_reschedule_fragmentation",
    "Stranded-free-capacity fraction at the last plan (pre = measured, "
    "post = projected over the selected moves)", ["phase"]))
reschedule_plan_solve_ms = registry.register(Gauge(
    "volcano_reschedule_plan_solve_milliseconds",
    "Wall time of the last defrag solve (snapshot + flatten + device "
    "solve + readback)"))
reschedule_intents_total = registry.register(Counter(
    "volcano_reschedule_intents_total",
    "Migration-intent journal activity (recorded / confirmed / settled "
    "/ abandoned)", ["event"]))

# -- cluster simulator metrics (sim/) ---------------------------------------

sim_cycles_total = registry.register(Counter(
    "volcano_sim_cycles_total",
    "Virtual scheduling cycles executed by the cluster simulator"))
sim_decisions_total = registry.register(Counter(
    "volcano_sim_decisions_total",
    "Decisions captured by the sim decision recorder", ["kind"]))
sim_replay_divergences_total = registry.register(Counter(
    "volcano_sim_replay_divergences_total",
    "Golden-trace verifications that found a divergence"))

# -- job / namespace metrics -----------------------------------------------

job_share = registry.register(Gauge(
    "volcano_job_share", "Share of job", ["job_ns", "job_id"]))
job_retry_counts = registry.register(Counter(
    "volcano_job_retry_counts", "Job retry counts", ["job_id"]))
namespace_share = registry.register(Gauge(
    "volcano_namespace_share", "Share of namespace", ["namespace_name"]))
namespace_weight = registry.register(Gauge(
    "volcano_namespace_weight", "Weight of namespace", ["namespace_name"]))


def update_queue_metrics(queue_name: str, allocated, request, deserved=None,
                         share: Optional[float] = None):
    queue_allocated_milli_cpu.set(allocated.milli_cpu, {"queue_name": queue_name})
    queue_allocated_memory_bytes.set(allocated.memory, {"queue_name": queue_name})
    queue_request_milli_cpu.set(request.milli_cpu, {"queue_name": queue_name})
    queue_request_memory_bytes.set(request.memory, {"queue_name": queue_name})
    if deserved is not None:
        queue_deserved_milli_cpu.set(deserved.milli_cpu, {"queue_name": queue_name})
        queue_deserved_memory_bytes.set(deserved.memory, {"queue_name": queue_name})
    if share is not None:
        queue_share.set(share, {"queue_name": queue_name})
