"""Reclaim action (reference actions/reclaim/reclaim.go:40-192).

Cross-queue: starving jobs of underused queues evict Running tasks of other,
reclaimable queues (tier-intersected Reclaimable fns). Evictions are
immediate (not statement-buffered), then the reclaimer pipelines.
"""

from __future__ import annotations

import logging
from typing import Dict

from ..api import Resource, TaskStatus
from ..framework import Action
from ..models import PodGroupPhase
from ..utils import PriorityQueue
from ..utils.scheduler_helper import validate_victims

log = logging.getLogger(__name__)


class ReclaimAction(Action):
    def name(self) -> str:
        return "reclaim"

    def execute(self, ssn) -> None:
        if self.resolve_mode(ssn) == "host":
            self._execute_host(ssn)
            return
        # per-job routing (mirrors allocate, ADVICE r2 #3)
        host_only = set(ssn.solver_options.get("host_only_jobs") or ())
        from .evict_solver import run_evict_solver
        if run_evict_solver(ssn, "reclaim", skip_jobs=host_only) is None:
            # device path unavailable (breaker open / solve failed):
            # degrade the whole action to the host loop for this cycle
            self._execute_host(ssn)
            return
        if host_only:
            self._execute_host(ssn, only_jobs=host_only)

    def _execute_host(self, ssn, only_jobs=None) -> None:
        from ..plugins.predicates import PredicateError

        queues = PriorityQueue(ssn.queue_order_fn)
        queue_set = set()
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            if only_jobs is not None and job.uid not in only_jobs:
                continue
            if job.pod_group.status.phase == PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_set:
                queue_set.add(queue.uid)
                queues.push(queue)
            pending = job.task_status_index.get(TaskStatus.PENDING, {})
            if pending:
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
                pq = PriorityQueue(ssn.task_order_fn)
                for task in pending.values():
                    pq.push(task)
                preemptor_tasks[job.uid] = pq

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue
            jobs = preemptors_map.get(queue.name)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            assigned = False
            for node in ssn.nodes.values():
                try:
                    ssn.predicate_fn(task, node)
                except PredicateError:
                    continue
                resreq = task.init_resreq.clone()
                reclaimed = Resource()
                reclaimees = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.RUNNING:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is None:
                        continue
                    if j.queue != job.queue:
                        q = ssn.queues.get(j.queue)
                        if q is None or not q.reclaimable:
                            continue
                        reclaimees.append(t.clone())
                victims = ssn.reclaimable(task, reclaimees)
                if validate_victims(task, node, victims) is not None:
                    continue
                for reclaimee in victims:
                    try:
                        ssn.evict(reclaimee, "reclaim")
                    except (KeyError, ValueError) as e:
                        log.warning("failed to reclaim %s: %s",
                                    reclaimee.key, e)
                        continue
                    reclaimed.add(reclaimee.resreq)
                    if resreq.less_equal(reclaimed):
                        break
                if task.init_resreq.less_equal(reclaimed):
                    try:
                        ssn.pipeline(task, node.name)
                    except (KeyError, ValueError):
                        log.warning("failed to pipeline %s on %s",
                                    task.key, node.name)
                    assigned = True
                    break
            if assigned:
                jobs.push(job)
            queues.push(queue)
