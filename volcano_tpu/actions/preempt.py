"""Preempt action (reference actions/preempt/preempt.go:41-262).

Within-queue: starving jobs (pending tasks, not pipelined) preempt Running
tasks of other jobs chosen by tier-intersected Preemptable fns; then
task-level preemption within each job. Statement-buffered: committed iff the
preemptor job reaches JobPipelined.
"""

from __future__ import annotations

import logging
from typing import Dict

from ..api import TaskStatus
from ..framework import Action
from ..metrics import metrics
from ..models import PodGroupPhase
from ..utils import PriorityQueue
from ..utils.scheduler_helper import validate_victims

log = logging.getLogger(__name__)


def _preempt_one(ssn, stmt, preemptor, node_filter) -> bool:
    """Try to free room for `preemptor` by evicting filtered victims
    (preempt.go:186-262)."""
    from ..plugins.predicates import PredicateError

    candidates = []
    for node in ssn.nodes.values():
        try:
            ssn.predicate_fn(preemptor, node)
        except PredicateError:
            continue
        candidates.append(node)
    scored = sorted(
        candidates,
        key=lambda n: ssn.node_order_fn(preemptor, n), reverse=True)

    for node in scored:
        preemptees = [t.clone() for t in node.tasks.values()
                      if node_filter(t)]
        victims = ssn.preemptable(preemptor, preemptees)
        metrics.preemption_victims.set(len(victims))
        err = validate_victims(preemptor, node, victims)
        if err is not None:
            continue
        # evict lowest-priority victims first
        victims_queue = PriorityQueue(
            lambda l, r: not ssn.task_order_fn(l, r))
        for v in victims:
            victims_queue.push(v)
        while not victims_queue.empty():
            if preemptor.init_resreq.less_equal(node.future_idle()):
                break
            victim = victims_queue.pop()
            try:
                stmt.evict(victim, "preempt")
            except (KeyError, ValueError) as e:
                log.warning("failed to preempt %s: %s", victim.key, e)
                continue
        metrics.preemption_attempts.inc()
        if preemptor.init_resreq.less_equal(node.future_idle()):
            stmt.pipeline(preemptor, node.name)
            return True
    return False


class PreemptAction(Action):
    def name(self) -> str:
        return "preempt"

    def execute(self, ssn) -> None:
        if self.resolve_mode(ssn) == "host":
            self._execute_host(ssn)
            return
        # per-job routing (mirrors allocate, ADVICE r2 #3): host-only
        # claimers run the host loop; everyone else solves on device
        host_only = set(ssn.solver_options.get("host_only_jobs") or ())
        from .evict_solver import run_evict_solver
        claimers = run_evict_solver(ssn, "preempt", skip_jobs=host_only)
        if claimers is None:
            # device path unavailable (breaker open / solve failed):
            # degrade the whole action to the host loop for this cycle
            self._execute_host(ssn)
            return
        if host_only:
            self._execute_host(ssn, only_jobs=host_only)
        # intra-job task-level preemption stays on the host path (small,
        # within one job's own tasks — preempt.go:137-156 second phase).
        # It runs on exactly the solver's claimer set (the host loop's
        # under_request: jobs that were not yet pipelined at collection).
        self._intra_job(ssn, claimers)

    def _intra_job(self, ssn, jobs) -> None:
        oc = getattr(ssn, "order_cache", None)
        for job in jobs:
            # same order, two sources: the OrderCache's version-gated
            # sorted pending list when the job is unchanged since the
            # last keyed allocate cycle, else the comparator heap (jobs
            # the solver phase just mutated always take this path)
            pending = oc.pending_tasks(ssn, job) if oc is not None \
                else None
            if pending is None:
                pq = PriorityQueue(ssn.task_order_fn)
                for task in job.task_status_index.get(
                        TaskStatus.PENDING, {}).values():
                    if not task.resreq.is_empty():
                        pq.push(task)
                pending = []
                while not pq.empty():
                    pending.append(pq.pop())
            for preemptor in pending:
                stmt = ssn.statement()

                def task_filter(task, preemptor=preemptor):
                    if task.status != TaskStatus.RUNNING:
                        return False
                    if task.resreq.is_empty():
                        return False
                    return preemptor.job == task.job

                assigned = _preempt_one(ssn, stmt, preemptor, task_filter)
                stmt.commit()
                if not assigned:
                    break

    def _execute_host(self, ssn, only_jobs=None) -> None:
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}
        under_request = []
        queues = {}

        for job in ssn.jobs.values():
            if only_jobs is not None and job.uid not in only_jobs:
                continue
            if job.pod_group.status.phase == PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            queues[queue.uid] = queue
            pending = job.task_status_index.get(TaskStatus.PENDING, {})
            if pending and not ssn.job_pipelined(job):
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
                under_request.append(job)
                pq = PriorityQueue(ssn.task_order_fn)
                for task in pending.values():
                    pq.push(task)
                preemptor_tasks[job.uid] = pq

        for queue in queues.values():
            # inter-job preemption within the queue
            while True:
                preemptors = preemptors_map.get(queue.name)
                if preemptors is None or preemptors.empty():
                    break
                preemptor_job = preemptors.pop()
                stmt = ssn.statement()
                assigned = False
                while True:
                    if ssn.job_pipelined(preemptor_job):
                        break
                    if preemptor_tasks[preemptor_job.uid].empty():
                        break
                    preemptor = preemptor_tasks[preemptor_job.uid].pop()

                    def job_filter(task, preemptor_job=preemptor_job,
                                   preemptor=preemptor):
                        if task.status != TaskStatus.RUNNING:
                            return False
                        if task.resreq.is_empty():
                            return False
                        job = ssn.jobs.get(task.job)
                        if job is None:
                            return False
                        return (job.queue == preemptor_job.queue
                                and preemptor.job != task.job)

                    if _preempt_one(ssn, stmt, preemptor, job_filter):
                        assigned = True
                if ssn.job_pipelined(preemptor_job):
                    stmt.commit()
                else:
                    stmt.discard()
                    continue
                if assigned:
                    preemptors.push(preemptor_job)

            # intra-job task-level preemption
            for job in under_request:
                while True:
                    pq = preemptor_tasks.get(job.uid)
                    if pq is None or pq.empty():
                        break
                    preemptor = pq.pop()
                    stmt = ssn.statement()

                    def task_filter(task, preemptor=preemptor):
                        if task.status != TaskStatus.RUNNING:
                            return False
                        if task.resreq.is_empty():
                            return False
                        return preemptor.job == task.job

                    assigned = _preempt_one(ssn, stmt, preemptor, task_filter)
                    stmt.commit()
                    if not assigned:
                        break
