"""Elect action (reference actions/elect/elect.go:29-51): pick the target
job for resource reservation."""

from __future__ import annotations

from ..framework import Action
from ..models import PodGroupPhase
from ..utils.scheduler_helper import reservation


class ElectAction(Action):
    def name(self) -> str:
        return "elect"

    def execute(self, ssn) -> None:
        if reservation.target_job is not None:
            return
        pending_jobs = [
            job for job in ssn.jobs.values()
            if job.pod_group.status.phase == PodGroupPhase.PENDING]
        reservation.target_job = ssn.target_job(pending_jobs)
