"""Allocate action: the hot path (reference actions/allocate/allocate.go:43-266).

Two execution modes:

- solver (default): collect pending tasks in the session's
  namespace/queue/job/task order (host-side comparators), flatten the
  decision problem into padded device arrays, run ops.solve_allocate on TPU,
  and replay the returned assignments through Statement/Pipeline — the
  ordering and transaction semantics stay in the control plane, the
  task x node math runs on device.
- host: a faithful per-task loop (predicate -> prioritize -> best node ->
  allocate/pipeline) used when custom host-only plugins are present, for
  parity testing, and as the reference semantics oracle.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from ..api import Resource, TaskStatus
from ..api.unschedule_info import (
    ALL_NODES_UNAVAILABLE, FitError, FitErrors, NODE_RESOURCE_FIT_FAILED,
)
from ..framework import Action
from ..models import PodGroupPhase
from ..utils import PriorityQueue

log = logging.getLogger(__name__)

_UNRESOLVED = object()  # sentinel: _pending_tasks resolves the key itself


def _task_order_key(ssn):
    """Full task-order key (pod creation-timestamp tiebreak) or None."""
    return ssn.full_order_key("task_order_fns",
                              ct_of=lambda t: t.pod.creation_timestamp)


def build_score_inputs(ssn, arr):
    """Resolve the session's plugin score weights against this flatten's
    vocab/shape: (params dict for ops.score_matrix, static families tuple)."""
    sp = ssn.score_params
    weights_fn = ssn.solver_options.get("binpack_vocab_weights")
    if weights_fn is not None:
        sp.binpack_res_weights = weights_fn(arr.vocab)
    rp = sp.resolved(arr.R, arr.N)
    params = {
        "binpack_weight": np.float32(rp.binpack_weight),
        "binpack_res_weights": rp.binpack_res_weights,
        "least_req_weight": np.float32(rp.least_req_weight),
        "most_req_weight": np.float32(rp.most_req_weight),
        "balanced_weight": np.float32(rp.balanced_weight),
        "node_static": rp.node_static,
    }
    families = []
    if rp.binpack_weight:
        families.append("binpack")
    if rp.least_req_weight or rp.most_req_weight or rp.balanced_weight:
        families.append("kube")
    if not families:
        families = ["kube"]
    return params, tuple(families)


class AllocateAction(Action):
    def name(self) -> str:
        return "allocate"

    # ------------------------------------------------------------------
    # shared: job/task ordering
    # ------------------------------------------------------------------

    def _ordered_jobs(self, ssn):
        """Yield schedulable jobs in namespace -> queue -> job order,
        skipping Pending-phase podgroups, invalid jobs, unknown queues and
        overused queues (allocate.go:61-160).

        When every active job-order plugin registered a key extractor the
        per-queue ordering is ONE sort by composite key instead of O(n log
        n) comparator dispatches — equivalent here because solver-mode
        collection happens before any session mutation, so the keys
        (shares, readiness) are frozen for its duration."""
        queue_factory = ssn.keyed_job_queue_factory() \
            or (lambda: PriorityQueue(ssn.job_order_fn))

        namespaces = PriorityQueue(ssn.namespace_order_fn)
        jobs_map: Dict[str, Dict[str, PriorityQueue]] = {}

        for job in ssn.jobs.values():
            # a job with no Pending tasks yields an empty task list and is
            # skipped by the caller anyway; filtering here keeps the
            # steady-state walk O(pending jobs), not O(all jobs) — at 1k
            # running jobs the full sort was most of the cycle's host time
            if TaskStatus.PENDING not in job.task_status_index:
                continue
            if job.pod_group.status.phase == PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            if job.queue not in ssn.queues:
                continue
            ns = job.namespace
            if ns not in jobs_map:
                jobs_map[ns] = {}
                namespaces.push(ns)
            jobs_map[ns].setdefault(job.queue, queue_factory()).push(job)

        while not namespaces.empty():
            ns = namespaces.pop()
            queue_map = jobs_map[ns]
            queue = None
            for qname in list(queue_map):
                qi = ssn.queues[qname]
                if ssn.overused(qi):
                    del queue_map[qname]
                    continue
                if queue is None or ssn.queue_order_fn(qi, queue):
                    queue = qi
            if queue is None:
                continue
            jobs = queue_map.get(queue.name)
            if jobs is None or jobs.empty():
                # Exhausted queue: drop it and rescan the namespace. The
                # reference instead relies on live share updates to steer
                # the next pick away (allocate.go:160-166 allocates inline);
                # this pre-solve collection has no updates, so an order tie
                # would starve every other queue's jobs out of the flatten.
                queue_map.pop(queue.name, None)
                namespaces.push(ns)
                continue
            job = jobs.pop()
            yield job
            namespaces.push(ns)

    def _pending_tasks(self, ssn, job, taskkey=_UNRESOLVED) -> List:
        """Pending, non-best-effort tasks in task order
        (allocate.go:175-189). ``taskkey`` is the full task-order key
        (resolve once per action via ssn.full_order_key and pass it in for
        multi-job loops; None falls back to comparator sorting). Jobs
        unchanged since the OrderCache's last keyed cycle reuse their
        cached sorted list (version-gated; a mutated or dirty job misses
        and re-sorts here)."""
        oc = getattr(ssn, "order_cache", None)
        if oc is not None:
            cached = oc.pending_tasks(ssn, job)
            if cached is not None:
                return cached
        pending = [
            t for t in job.task_status_index.get(
                TaskStatus.PENDING, {}).values()
            if not t.resreq.is_empty()  # BestEffort tasks are backfill's
        ]
        if taskkey is _UNRESOLVED:
            taskkey = _task_order_key(ssn)
        if taskkey is not None:
            pending.sort(key=taskkey)
            return pending
        pq = PriorityQueue(ssn.task_order_fn)
        for task in pending:
            pq.push(task)
        out = []
        while not pq.empty():
            out.append(pq.pop())
        return out

    def _collect(self, ssn) -> List:
        """[(job, sorted pending tasks), ...] in session order: the
        event-sourced OrderCache when it can serve this conf (patching
        only event-dirty jobs — O(changes), not O(pending)), else the
        live comparator walk above. Both produce the identical sequence;
        the cache degrades itself with a typed reason on anything it
        cannot prove (ops.ordering)."""
        oc = getattr(ssn, "order_cache", None)
        if oc is not None:
            try:
                collected = oc.collect(ssn)
            except Exception:  # noqa: BLE001 — degrade, don't contain
                log.exception("order cache failed; dropping it and "
                              "collecting via the live comparator walk")
                oc.invalidate("order_cache_error")
                collected = None
            if collected is not None:
                return collected
        taskkey = _task_order_key(ssn)
        return [(job, self._pending_tasks(ssn, job, taskkey))
                for job in self._ordered_jobs(ssn)]

    # ------------------------------------------------------------------
    # solver mode
    # ------------------------------------------------------------------

    def _execute_solver(self, ssn, sequential: bool = False,
                        sharded: bool = False) -> None:
        import time as _time

        from ..ops import flatten_snapshot, solve_allocate, \
            solve_allocate_sequential

        from ..resilience import faults

        timing = ssn.solver_options.setdefault("timing", {})
        breaker = getattr(ssn, "breaker", None)
        t0 = _time.perf_counter()
        host_only = ssn.solver_options.get("host_only_jobs") or ()
        job_order = []
        tasks_in_order = []
        # the ordering pass: event-sourced when the OrderCache can serve
        # this conf (O(changes since last cycle)), the live comparator
        # walk otherwise — surfaced per cycle as order_{mode,ms,
        # entries_patched,fallback_reason}
        collected = self._collect(ssn)
        order_ms = (_time.perf_counter() - t0) * 1e3
        timing["order_ms"] = order_ms
        oc = getattr(ssn, "order_cache", None)
        if oc is not None:
            timing["order_mode"] = oc.last_mode
            timing["order_entries_patched"] = \
                float(oc.last_entries_patched)
            if oc.last_reason:
                timing["order_fallback_reason"] = oc.last_reason
        # host-only jobs (GPU sharing, required pod affinity, PVCs) that
        # OUTRANK every device-path job run through the host loop BEFORE
        # the solve, so per-job routing cannot invert priority (a
        # top-priority GPU gang must not find its CPU eaten by
        # lower-priority solver placements). Host-only jobs ranked mid
        # -sequence still run after — an accepted coarsening of the
        # reference's fully sequential order, noted in the contract.
        pre_host, post_host = [], []
        for job, tasks in collected:
            if job.uid in host_only:
                (post_host if job_order else pre_host).append(job.uid)
                continue
            if tasks:
                job_order.append((job, tasks))
                tasks_in_order.extend(tasks)
        ssn.solver_options["_post_host_jobs"] = post_host
        if pre_host:
            self._execute_host(ssn, only_jobs=set(pre_host))
        if not tasks_in_order:
            return

        fc = getattr(ssn, "flatten_cache", None)
        if fc is not None and getattr(fc, "events_enabled", False) \
                and getattr(ssn, "_mutation_ops", 0):
            # an earlier action in this cycle already mutated the session's
            # clones; those deltas never reached the event ledger, so the
            # event-sourced fast path must re-diff this cycle
            fc.suppress_event_path("session_mutations")
        t_fs = _time.perf_counter()
        arr = flatten_snapshot(
            {j.uid: j for j, _ in job_order}, ssn.nodes, tasks_in_order,
            queues=ssn.queues, cache=fc, grouped=job_order)
        fs_ms = (_time.perf_counter() - t_fs) * 1e3
        if fc is not None:
            # the event -> incremental -> cold ladder made observable:
            # which assembly path this cycle's flatten took, how many rows
            # it patched, and the patch-vs-full-pass latency split
            timing["flatten_mode"] = fc.last_flatten_mode
            timing["flatten_rows_patched"] = float(fc.last_rows_patched)
            timing["flatten_events_applied"] = \
                float(fc.last_events_applied)
            if fc.last_flatten_mode == "event":
                timing["flatten_patch_ms"] = fs_ms
            else:
                timing["flatten_full_ms"] = fs_ms
            if fc.last_fallback_reason:
                timing["flatten_fallback_reason"] = fc.last_fallback_reason

        # queue fairness: when proportion is active its session-open attrs
        # (allocated/request over ALL jobs, incl. running-only queues) feed
        # the in-kernel water-fill + per-round deserved caps
        queue_opts = ssn.solver_options.get("queue_opts")
        use_queue_cap = bool(queue_opts)
        work_conserving = bool(
            ssn.solver_options.get("work_conserving", True))
        if use_queue_cap:
            self._fill_queue_arrays(arr, queue_opts, ssn)

        # live DRF ordering on device (drf plugin active): the kernel
        # re-ranks jobs by dominant share every round. Job-order providers
        # dispatched BEFORE drf in the tiers (priority, gang) compose as a
        # static MAJOR rank (arr.job_drf_prerank) that live shares only
        # tie-break — the reference's comparator chain returns on the
        # first non-zero, so strict priorities dominate and equal
        # priorities fall through to drf, which the kernel now mirrors
        # instead of disabling the re-rank outright (a disabled re-rank
        # froze the snapshot order and could starve later-created jobs
        # under the default priority-before-drf conf). Falls back to the
        # static order only when a preceding provider registered no sort
        # key.
        drf_opts = ssn.solver_options.get("drf_order")
        use_drf_order = bool(drf_opts) and not sequential
        if use_drf_order:
            providers = [name for _, name, _
                         in ssn._tier_fns("job_order_fns")]
            if "drf" not in providers:
                use_drf_order = False
            else:
                pre = providers[:providers.index("drf")]
                keyfns = [ssn.order_key_fns.get(
                    "job_order_fns", {}).get(p) for p in pre]
                if any(kf is None for kf in keyfns):
                    use_drf_order = False
                elif keyfns:
                    keys = [tuple(kf(job) for kf in keyfns)
                            for job in arr.jobs_list]
                    order = sorted(range(len(keys)), key=keys.__getitem__)
                    # dense rank; EQUAL key tuples share a rank so shares
                    # can tie-break across them
                    prev = None
                    rank_val = -1
                    for j in order:
                        if keys[j] != prev:
                            rank_val += 1
                            prev = keys[j]
                        arr.job_drf_prerank[j] = rank_val
        use_hdrf_order = False
        if use_drf_order:
            attrs = drf_opts["job_attrs"]
            for j, job in enumerate(arr.jobs_list):
                attr = attrs.get(job.uid)
                if attr is not None:
                    arr.job_drf_allocated[j] = \
                        attr.allocated.to_vector(arr.vocab)
            arr.drf_total = drf_opts["total"].to_vector(arr.vocab)
            if drf_opts.get("hierarchy"):
                from ..ops.hdrf import build_hdrf
                build_hdrf(arr, ssn.queues, attrs,
                           drf_opts["total_allocated"])
                use_hdrf_order = True

        timing["flatten_ms"] = (_time.perf_counter() - t0) * 1e3
        t0 = _time.perf_counter()
        params, families = build_score_inputs(ssn, arr)
        herd = ssn.solver_options.get("herd_mode")
        if herd is None:
            herd = "pack" if params["binpack_weight"] > (
                params["least_req_weight"]
                + params["balanced_weight"]) else "spread"

        dc = getattr(ssn, "device_cache", None)
        sidecar = getattr(ssn, "sidecar", None)
        # which arena a device fault must invalidate: the packed cache by
        # default, the sharded arena when this session dispatched there
        fault_dc = dc
        try:
            # device-path circuit-breaker scope: anything that throws out
            # of the dispatch (XLA runtime error, OOM, dead sidecar, an
            # injected fault) counts one consecutive device failure and
            # this session finishes through the host oracle
            faults.fire("solver_dispatch")
            if sequential:
                res = solve_allocate_sequential(
                    arr.device_dict(), params, score_families=families,
                    use_queue_cap=use_queue_cap,
                    work_conserving=work_conserving)
            elif sharded:
                # mode: sharded — the node-axis shard_map solver over the
                # SHARDED device-resident arena (ShardedDeviceCache):
                # node-axis chunks live per mesh device, task/job chunks
                # are replicated once per device, and a steady session
                # ships dirty chunks only to the shard(s) owning them
                # (a zero-dirty session dispatches straight off the
                # resident shards, 0 bytes). At D=1 the mesh degrades to
                # the packed arena's shape with a collective-free program;
                # multi-chip deployments get the identical code path with
                # a wider mesh. The dispatch keeps the packed path's whole
                # protection ladder: one transient-transport retry (a
                # dropped remote_compile stream re-sends instead of
                # burning a breaker failure — BENCH_r05's abort mode),
                # the circuit breaker + host-oracle fallback around this
                # block, and the async-readback overlap below.
                from ..parallel import (
                    arena_mesh, solve_allocate_sharded_arena,
                )
                from ..resilience.transient import retry_transient
                t1 = _time.perf_counter()
                fbuf, ibuf, layout = arr.packed()
                timing["pack_ms"] = (_time.perf_counter() - t1) * 1e3
                sdc = getattr(ssn, "sharded_device_cache", None)
                if sdc is None:
                    from ..ops.device_cache import ShardedDeviceCache
                    sdc = ShardedDeviceCache(arena_mesh())
                    ssn.sharded_device_cache = sdc
                    if getattr(ssn, "cache", None) is not None:
                        # persist across sessions: an arena is only an
                        # arena if it outlives the session that built it
                        ssn.cache.sharded_device_cache = sdc
                fault_dc = sdc
                mesh = sdc.mesh
                t1 = _time.perf_counter()
                bufs = sdc.update(fbuf, ibuf, layout)
                params = sdc.params_device(params)
                timing["delta_plan_ms"] = (_time.perf_counter() - t1) * 1e3
                timing["delta_chunks"] = float(sdc.last_shipped_chunks)
                timing["arena_mode"] = "sharded"
                timing["arena_bytes_shipped"] = \
                    float(sdc.last_shipped_bytes)
                timing["arena_full_ship"] = float(sdc.last_full_ship)
                timing["arena_shard_bytes"] = \
                    [float(b) for b in sdc.last_shard_bytes]
                pw = getattr(ssn, "prewarmer", None)
                if pw is not None and pw.mesh is None:
                    # sharded sessions must pre-warm (and persistent-
                    # cache) the sharded arena variants too, not just
                    # packed2d
                    pw.mesh = mesh
                # flags snapshot so the bucket prewarmer can predict this
                # mode's next-bucket variants
                sdc.last_solve_flags = dict(
                    layout=layout, herd_mode=herd,
                    score_families=families,
                    use_queue_cap=use_queue_cap,
                    use_drf_order=use_drf_order,
                    use_hdrf_order=use_hdrf_order,
                    work_conserving=work_conserving)
                t1 = _time.perf_counter()
                r = retry_transient(
                    lambda: solve_allocate_sharded_arena(
                        *bufs, params, mesh, herd_mode=herd,
                        score_families=families,
                        use_queue_cap=use_queue_cap,
                        use_drf_order=use_drf_order,
                        use_hdrf_order=use_hdrf_order),
                    what="sharded solver dispatch")
                timing["dispatch_ms"] = (_time.perf_counter() - t1) * 1e3
                # the sharded kernel produces no compact readback:
                # assigned/kind stay DEVICE futures here and collect in
                # the res-is-None branch below, after the overlap window
                assigned = r.assigned
                kind = r.kind
                res = None
            elif sidecar is not None:
                # process boundary: ship the packed snapshot to the solver
                # sidecar (which owns the TPU) and replay its assignments
                fbuf, ibuf, layout = arr.packed()
                assigned, kind, _info = sidecar.solve(
                    fbuf, ibuf, layout, params, herd_mode=herd,
                    score_families=families, use_queue_cap=use_queue_cap,
                    use_drf_order=use_drf_order,
                    use_hdrf_order=use_hdrf_order,
                    work_conserving=work_conserving)
                res = None
            elif dc is not None:
                # device-resident buffers, fused dispatch: the dirty-chunk
                # scatter runs INSIDE the solve jit, so a session costs
                # exactly one dispatch (scatter+solve) + one compact
                # readback. Sessions dirtying more than FUSED_SLOTS chunks
                # use the separate scatter + non-fused solve (3
                # dispatches, but no extra solve compile variants)
                from ..ops.solver import (
                    solve_allocate_delta, solve_allocate_packed2d,
                )
                t1 = _time.perf_counter()
                fbuf, ibuf, layout = arr.packed()
                timing["pack_ms"] = (_time.perf_counter() - t1) * 1e3
                params = dc.params_device(params)
                # flags snapshot for diagnostics/benchmarks that
                # re-dispatch the same solve variant against the
                # committed buffers
                dc.last_solve_flags = dict(
                    layout=layout, herd_mode=herd, score_families=families,
                    use_queue_cap=use_queue_cap,
                    use_drf_order=use_drf_order,
                    use_hdrf_order=use_hdrf_order,
                    work_conserving=work_conserving)
                dc.last_params = params
                t1 = _time.perf_counter()
                kind_, payload = dc.plan_delta(fbuf, ibuf, layout)
                timing["delta_plan_ms"] = (_time.perf_counter() - t1) * 1e3
                timing["delta_chunks"] = float(dc.last_shipped_chunks)
                timing["delta_fused"] = float(kind_ == "fused")
                timing["arena_mode"] = "packed"
                timing["arena_bytes_shipped"] = float(dc.last_shipped_bytes)
                timing["arena_full_ship"] = float(dc.last_full_ship)
                t1 = _time.perf_counter()
                if kind_ == "updated":
                    f2d, i2d = payload
                    res = solve_allocate_packed2d(
                        f2d, i2d, layout, params, herd_mode=herd,
                        score_families=families,
                        use_queue_cap=use_queue_cap,
                        use_drf_order=use_drf_order,
                        use_hdrf_order=use_hdrf_order,
                        work_conserving=work_conserving)
                else:
                    f2d, i2d, fi, fv, ii, iv = payload
                    try:
                        res, new_f, new_i = solve_allocate_delta(
                            f2d, i2d, fi, fv, ii, iv, layout, params,
                            herd_mode=herd, score_families=families,
                            use_queue_cap=use_queue_cap,
                            use_drf_order=use_drf_order,
                            use_hdrf_order=use_hdrf_order,
                            work_conserving=work_conserving)
                    except Exception:
                        # donation may have consumed the buffers — but the
                        # host mirror and the (never-donated) pinned params
                        # are fine: soft-invalidate so the next session
                        # re-ships the chunked buffers and re-validates the
                        # params in place instead of rebuilding cold
                        dc.invalidate()
                        raise
                    dc.commit(new_f, new_i)
                timing["dispatch_ms"] = (_time.perf_counter() - t1) * 1e3
            else:
                res = solve_allocate(
                    arr.device_dict(), params, herd_mode=herd,
                    score_families=families, use_queue_cap=use_queue_cap,
                    use_drf_order=use_drf_order,
                    use_hdrf_order=use_hdrf_order,
                    work_conserving=work_conserving)
        except Exception:
            log.exception("solver dispatch failed; resetting the device "
                          "cache and falling back to the host loop")
            self._device_fault_fallback(ssn, fault_dc, timing, breaker)
            return
        # ------------------------------------------------------------------
        # dispatch/collect split: the jitted solve above is an ASYNC
        # dispatch (res holds device futures), so the host is free until
        # the compact readback below actually blocks. Spend that window on
        # work that previously serialized after the device finished:
        # replay preparation (the node-name table the Statement replay
        # indexes), the bucket-prewarm occupancy check (ops.precompile),
        # and a young-generation gc pass (collection is disabled during
        # the cycle — see Scheduler.run_once — so this drains the nursery
        # for free while the device solves). pipeline_solver=False keeps
        # the strictly serial order for parity testing.
        # ------------------------------------------------------------------
        pipelined = bool(getattr(ssn, "pipeline_solver", True))
        node_names = None
        statements = None
        prewarmed = False
        if pipelined and (res is not None or sharded):
            t1 = _time.perf_counter()
            # previous-phase readback starts NOW: begin the device->host
            # result transfer asynchronously so the wire RTT overlaps the
            # solve tail and the replay-prep below instead of being paid
            # serially when the collect blocks (ops.pipeline). The
            # sharded kernel has no compact form; its assigned/kind
            # futures prefetch the same way.
            from ..ops.pipeline import start_readback
            if res is not None:
                start_readback(res.compact, res.assigned, res.kind)
            else:
                start_readback(assigned, kind)
            node_names = [n.name for n in arr.nodes_list]
            # Statement construction is pure (no session registration
            # until ops are recorded), so the replay's per-job statements
            # can be built before the results exist
            statements = [ssn.statement(defer_events=True)
                          for _ in job_order]
            self._observe_prewarm(ssn, arr, fault_dc)
            prewarmed = True
            import jax
            if jax.default_backend() != "cpu":
                # young-gen GC only when the solve runs on a real
                # accelerator: there the readback wait is genuine host
                # idle, while on the CPU backend host and "device" share
                # cores and the collection would just lengthen the cycle
                import gc
                gc.collect(0)
            timing["overlap_ms"] = (_time.perf_counter() - t1) * 1e3
        if res is not None:
            # one int16 readback instead of two int32 ones: the tunnel to a
            # remote chip is bandwidth-poor, so the result wire format
            # matters (the sidecar path already returned host arrays)
            from ..ops.solver import COMPACT_KIND_SHIFT, decode_compact
            t1 = _time.perf_counter()
            try:
                if arr.N <= (1 << COMPACT_KIND_SHIFT):
                    assigned, kind = decode_compact(res.compact)
                else:  # >16k nodes: node index overflows int16 packing
                    assigned = np.asarray(res.assigned)
                    kind = np.asarray(res.kind)
                self._check_solver_output(assigned, kind,
                                          len(tasks_in_order),
                                          len(arr.nodes_list))
            except Exception:
                # async-collect failure: the error surfaces HERE, after a
                # donated-buffer dispatch already commit()ed what are now
                # poisoned device buffers — drop the device cache so the
                # next session re-ships in full instead of solving on (or
                # scattering into) invalid buffers, and finish THIS
                # session through the host oracle so a device fault costs
                # one slow cycle, not a scheduling gap
                log.exception("solver collect failed; resetting device "
                              "cache and falling back to the host loop")
                self._device_fault_fallback(ssn, fault_dc, timing, breaker)
                return
            timing["readback_ms"] = (_time.perf_counter() - t1) * 1e3
            if not pipelined:
                # serial mode still pre-warms (after the readback), so
                # turning the overlap off doesn't also disable the
                # compile-stall protection
                self._observe_prewarm(ssn, arr, dc)
        else:
            # sharded/sidecar path: block on the assigned/kind readback
            # (the sidecar already returned host arrays; the sharded
            # overlap window above began the async device->host transfer,
            # so this collect pays only the remaining tail)
            t1 = _time.perf_counter()
            try:
                assigned = np.asarray(assigned)
                kind = np.asarray(kind)
                self._check_solver_output(assigned, kind,
                                          len(tasks_in_order),
                                          len(arr.nodes_list))
            except Exception:
                log.exception("sharded/sidecar solver output failed "
                              "validation; falling back to the host loop")
                self._device_fault_fallback(ssn, fault_dc, timing, breaker)
                return
            timing["readback_ms"] = (_time.perf_counter() - t1) * 1e3
            if not prewarmed:
                # the sidecar (and serial sharded) path skipped the
                # overlap window above, so the occupancy check runs here
                # — a sharded session's bucket crossing must pre-warm its
                # own (sharded) variants
                self._observe_prewarm(ssn, arr, fault_dc)
        if breaker is not None:
            # a full dispatch+collect round-trip with sane output: the
            # device path is healthy (closes a half-open breaker)
            breaker.record_success()
        timing["solve_ms"] = (_time.perf_counter() - t0) * 1e3
        t0 = _time.perf_counter()

        # replay through the Statement boundary in job order; events fire
        # as one batch per committed job and each job's accounting applies
        # as one bulk Statement wave (identical final handler/session state
        # — see Statement.allocate_bulk — at a fraction of the per-task
        # cost; the per-task loop blew the 1 s period on a 10k burst)
        assigned = assigned.tolist()  # plain ints: no np scalar per lookup
        kind = kind.tolist()
        # bulk-commit window: committed statements queue their cache-side
        # binds + allocate events; ONE flush applies them with full-width
        # node grouping (per-job commits degrade to 1-task node groups
        # when gangs spread across nodes — see Statement.commit)
        from ..framework.statement import begin_bulk_commit, \
            flush_bulk_commit
        acc = begin_bulk_commit(ssn)
        try:
            self._replay(ssn, arr, job_order, assigned, kind, node_names,
                         statements)
        finally:
            # exception-safe: jobs already committed into the window MUST
            # still get their cache binds + events even if a later job's
            # replay blows up (per-statement commits applied them eagerly)
            flush_bulk_commit(ssn, acc)
        timing["replay_ms"] = (_time.perf_counter() - t0) * 1e3

    def _device_fault_fallback(self, ssn, dc, timing, breaker) -> None:
        """Shared device-failure containment: count the failure against
        the circuit breaker, invalidate the (possibly poisoned) donated
        device buffers — keeping the host mirror and the never-donated
        pinned params for re-validation next session — and finish THIS
        session through the host oracle: a device fault costs one slow
        cycle plus one full re-ship, never a scheduling gap or a
        permanently cold arena (degradation ladder: device -> host
        oracle -> skip cycle)."""
        if breaker is not None:
            breaker.record_failure()
        if dc is not None:
            dc.invalidate()
        timing["host_fallback"] = 1.0
        ssn.solver_options["_post_host_jobs"] = []
        self._execute_host(ssn)

    @staticmethod
    def _check_solver_output(assigned, kind, n_tasks: int,
                             n_nodes: int) -> None:
        """Reject garbage readbacks (a sick device can return buffers
        full of nonsense without raising): node indices must be in
        [-1, n_nodes) and the pipeline flag boolean for every real task.
        Raising here routes through the same collect-failure fallback as
        an exception from the device itself."""
        a = np.asarray(assigned)[:n_tasks]
        k = np.asarray(kind)[:n_tasks]
        if not np.isfinite(a.astype(np.float64)).all():
            raise RuntimeError("solver returned non-finite assignments")
        if a.size and (((a < -1) | (a >= n_nodes)).any()
                       or ((a >= 0) & (k != 0) & (k != 1)).any()):
            raise RuntimeError(
                "solver output failed sanity checks (node index out of "
                f"[-1, {n_nodes}) or non-boolean pipeline flag)")

    @staticmethod
    def _observe_prewarm(ssn, arr, dc) -> None:
        """Feed the bucket prewarmer (ops.precompile.BucketPrewarmer) the
        live occupancy; a trigger only spawns a daemon thread, so this is
        safe inside the dispatch/collect overlap window."""
        pw = getattr(ssn, "prewarmer", None)
        if pw is None or dc is None:
            return
        try:
            pw.observe(arr, dc)
        except Exception:  # noqa: BLE001 — prewarm is advisory
            log.exception("bucket prewarm observe failed")

    def _replay(self, ssn, arr, job_order, assigned, kind,
                node_names: Optional[List[str]] = None,
                statements: Optional[List] = None) -> None:
        # node-name table + per-job statements: prepped in the
        # dispatch/collect overlap window when the pipeline is on,
        # rebuilt here otherwise
        if node_names is None:
            node_names = [n.name for n in arr.nodes_list]
        idx = 0
        for j, (job, tasks) in enumerate(job_order):
            stmt = statements[j] if statements is not None \
                else ssn.statement(defer_events=True)
            pairs = []
            for task in tasks:
                t_idx = idx
                idx += 1
                node_idx = assigned[t_idx]
                if node_idx < 0:
                    fe = FitErrors()
                    fe.set_error(ALL_NODES_UNAVAILABLE)
                    job.nodes_fit_errors[task.key] = fe
                    continue
                node_name = node_names[node_idx]
                if kind[t_idx] == 0:
                    pairs.append((task, node_name))
                    continue
                try:
                    ssn.pipeline(task, node_name)
                except (KeyError, ValueError) as e:
                    log.exception("replay failed for %s", task.key)
                    fe = FitErrors()
                    fe.set_node_error(node_name, FitError(
                        task, node_name, [str(e)]))
                    job.nodes_fit_errors[task.key] = fe
            for task, node_name, e in stmt.allocate_bulk(pairs):
                log.error("replay failed for %s", task.key, exc_info=e)
                fe = FitErrors()
                fe.set_node_error(node_name, FitError(
                    task, node_name, [str(e)]))
                job.nodes_fit_errors[task.key] = fe
            if ssn.job_ready(job):
                stmt.commit()
            else:
                stmt.discard()

    @staticmethod
    def _fill_queue_arrays(arr, queue_opts, ssn) -> None:
        """Overwrite the flatten's queue arrays from the proportion plugin's
        per-queue attrs (weight/capability/allocated/request). Queues known
        to the plugin but absent from the pending flatten (running-only
        queues) still participate in the water-fill, so their weight share
        is not redistributed to hungry queues (proportion.go:137-167)."""
        from ..ops.arrays import bucket

        vocab = arr.vocab
        R = len(vocab)
        names = list(arr.queues_list)
        known = set(names)
        names += [n for n in queue_opts if n not in known]
        Q = bucket(max(len(names), 1))
        weight = np.zeros(Q, dtype=np.float32)
        cap = np.full((Q, R), np.inf, dtype=np.float32)
        alloc = np.zeros((Q, R), dtype=np.float32)
        req = np.zeros((Q, R), dtype=np.float32)
        for i, n in enumerate(names):
            attr = queue_opts.get(n)
            if attr is None:
                qi = ssn.queues.get(n)
                weight[i] = getattr(qi, "weight", 1) or 1
                req[i] = np.inf  # unknown demand: stays hungry
                continue
            weight[i] = attr.weight
            alloc[i] = attr.allocated.to_vector(vocab)
            req[i] = attr.request.to_vector(vocab)
            if attr.capability is not None:
                cap_vec = attr.capability.to_vector(vocab)
                cap[i] = np.where(cap_vec > 0, cap_vec, np.inf)
        arr.queue_weight = weight
        arr.queue_capability = cap
        arr.queue_allocated = alloc
        arr.queue_request = req

    # ------------------------------------------------------------------
    # host mode (reference per-task loop)
    # ------------------------------------------------------------------

    def _predicate(self, ssn, task, node) -> None:
        if not task.init_resreq.less_equal(node.future_idle()):
            from ..plugins.predicates import PredicateError
            raise PredicateError(
                FitError(task, node.name, [NODE_RESOURCE_FIT_FAILED]))
        ssn.predicate_fn(task, node)

    def _execute_host(self, ssn, only_jobs=None) -> None:
        from ..plugins.predicates import PredicateError

        # Faithful control-flow port of allocate.go:124-265: the namespace
        # loop pops one job per iteration, requeues a ready job with
        # remaining tasks, and re-picks the queue each round so share-driven
        # orders (drf/hdrf/proportion) steer every single placement.
        # only_jobs restricts the loop to the jobs the solver routed here
        # (required inter-pod affinity needs in-flight placement tracking).
        namespaces = PriorityQueue(ssn.namespace_order_fn)
        jobs_map: Dict[str, Dict[str, PriorityQueue]] = {}
        for job in ssn.jobs.values():
            if only_jobs is not None and job.uid not in only_jobs:
                continue
            if TaskStatus.PENDING not in job.task_status_index:
                continue  # nothing to place (see _ordered_jobs)
            if job.pod_group.status.phase == PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            if job.queue not in ssn.queues:
                continue
            ns = job.namespace
            if ns not in jobs_map:
                jobs_map[ns] = {}
                namespaces.push(ns)
            jobs_map[ns].setdefault(
                job.queue, PriorityQueue(ssn.job_order_fn)).push(job)

        pending_tasks: Dict[str, List] = {}
        while not namespaces.empty():
            ns = namespaces.pop()
            queue_map = jobs_map[ns]
            queue = None
            for qname in list(queue_map):
                qi = ssn.queues[qname]
                if ssn.overused(qi):
                    del queue_map[qname]
                    continue
                if queue is None or ssn.queue_order_fn(qi, queue):
                    queue = qi
            if queue is None:
                continue
            jobs = queue_map.get(queue.name)
            if jobs is None or jobs.empty():
                # drained queue: drop it and keep the namespace live so
                # its OTHER queues still pop (allocate.go:165-171 pops
                # the empty queue off the heap and continues; dropping
                # the namespace here would strand every sibling queue)
                queue_map.pop(queue.name, None)
                if any(not q.empty() for q in queue_map.values()):
                    namespaces.push(ns)
                continue
            job = jobs.pop()
            if job.uid not in pending_tasks:
                pending_tasks[job.uid] = self._pending_tasks(ssn, job)
            tasks = pending_tasks[job.uid]

            stmt = ssn.statement()
            sampler = getattr(ssn, "node_sampler", None)
            while tasks:
                task = tasks.pop(0)
                fit_errors = FitErrors()
                candidates = []
                all_nodes = list(ssn.nodes.values())
                if sampler is not None:
                    node_list, want = sampler.plan(all_nodes)
                else:
                    node_list, want = all_nodes, len(all_nodes)
                visited = 0
                for node in node_list:
                    visited += 1
                    try:
                        self._predicate(ssn, task, node)
                        candidates.append(node)
                        if len(candidates) >= want:
                            break  # adaptive sampling: enough feasible nodes
                    except PredicateError as e:
                        fit_errors.set_node_error(node.name, e.fit_error)
                if sampler is not None:
                    sampler.advance(visited, len(all_nodes))
                if not candidates:
                    job.nodes_fit_errors[task.key] = fit_errors
                    break
                candidates = [
                    n for n in candidates
                    if task.init_resreq.less_equal(n.idle)
                    or task.init_resreq.less_equal(n.future_idle())]
                if not candidates:
                    continue
                scores = {n.name: ssn.node_order_fn(task, n)
                          for n in candidates}
                batch = ssn.batch_node_order_fn(task, candidates)
                for name, s in batch.items():
                    scores[name] = scores.get(name, 0.0) + s
                best = ssn.best_node_fn(task, scores)
                if best is None:
                    best = max(candidates, key=lambda n: scores[n.name])
                try:
                    if task.init_resreq.less_equal(best.idle):
                        stmt.allocate(task, best.name)
                    else:
                        ssn.pipeline(task, best.name)
                except ValueError as e:
                    # e.g. AllocateVolumes failure (allocate.go:232-237
                    # logs and moves on; the resync path re-tries later)
                    log.warning("allocate failed for %s on %s: %s",
                                task.key, best.name, e)
                    continue
                if ssn.job_ready(job) and tasks:
                    jobs.push(job)
                    break
            if ssn.job_ready(job):
                stmt.commit()
            else:
                stmt.discard()
            namespaces.push(ns)

    def execute(self, ssn) -> None:
        mode = self.resolve_mode(ssn)
        breaker = getattr(ssn, "breaker", None)
        if mode != "host" and breaker is not None and not breaker.allow():
            # device path circuit-broken: go straight to the host oracle
            # for this cycle instead of paying a doomed dispatch (the
            # cool-down's half-open probe re-tries the device path later)
            timing = ssn.solver_options.setdefault("timing", {})
            timing["host_fallback"] = 1.0
            timing["breaker_open"] = 1.0
            breaker.count_fallback()
            mode = "host"
        if mode == "host":
            self._execute_host(ssn)
            return
        self._execute_solver(ssn, sequential=(mode == "sequential"),
                             sharded=(mode == "sharded"))
        host_only = ssn.solver_options.get("host_only_jobs")
        if host_only:
            # host-only jobs ranked after some device-path job place via
            # the host loop against the post-solve session state (required
            # pod affinity wants other placements visible); the outranking
            # ones already placed BEFORE the solve in _execute_solver
            post = ssn.solver_options.get("_post_host_jobs")
            only = set(post) if post is not None else set(host_only)
            if only:
                self._execute_host(ssn, only_jobs=only)
