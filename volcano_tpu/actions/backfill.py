"""Backfill action (reference actions/backfill/backfill.go:40-93).

Best-effort tasks (empty launch request) are placed on the first node that
passes predicates, immediately via ssn.allocate (no statement — backfill is
not gang-protected).
"""

from __future__ import annotations

import logging

from ..api import TaskStatus
from ..api.unschedule_info import FitErrors
from ..framework import Action
from ..models import PodGroupPhase

log = logging.getLogger(__name__)


class BackfillAction(Action):
    def name(self) -> str:
        return "backfill"

    def execute(self, ssn) -> None:
        from ..plugins.predicates import PredicateError

        for job in ssn.jobs.values():
            if TaskStatus.PENDING not in job.task_status_index:
                continue  # no pending tasks -> nothing to backfill
            if job.pod_group.status.phase == PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            for task in list(job.task_status_index.get(
                    TaskStatus.PENDING, {}).values()):
                if not task.init_resreq.is_empty():
                    continue
                fe = FitErrors()
                allocated = False
                for node in ssn.nodes.values():
                    try:
                        ssn.predicate_fn(task, node)
                    except PredicateError as e:
                        fe.set_node_error(node.name, e.fit_error)
                        continue
                    try:
                        ssn.allocate(task, node.name)
                        allocated = True
                        break
                    except (KeyError, ValueError) as e:
                        log.warning("backfill bind failed for %s on %s: %s",
                                    task.key, node.name, e)
                        continue
                if not allocated:
                    job.nodes_fit_errors[task.key] = fe
