"""Enqueue action (reference actions/enqueue/enqueue.go:56-174).

Pending PodGroups go Inqueue when the cluster's overcommitted idle can hold
their MinResources and every JobEnqueueable fn passes.
"""

from __future__ import annotations

from ..api import Resource
from ..framework import Action, Arguments
from ..models import PodGroupPhase
from ..utils import PriorityQueue

DEFAULT_OVERCOMMIT_FACTOR = 1.2


class EnqueueAction(Action):
    def name(self) -> str:
        return "enqueue"

    def _overcommit_factor(self, ssn) -> float:
        for conf in ssn.configurations:
            if conf.name == self.name():
                return Arguments(conf.arguments).get_float(
                    "overcommit-factor", DEFAULT_OVERCOMMIT_FACTOR)
        return DEFAULT_OVERCOMMIT_FACTOR

    def execute(self, ssn) -> None:
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_set = set()
        jobs_map = {}
        # ordering keys are frozen during enqueue (nothing allocates), so
        # the key-sorted queue applies whenever the plugins provide keys
        job_queue_factory = ssn.keyed_job_queue_factory() \
            or (lambda: PriorityQueue(ssn.job_order_fn))

        import time
        for job in ssn.jobs.values():
            if job.schedule_start_timestamp is None:
                job.schedule_start_timestamp = time.time()
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_set:
                queue_set.add(queue.uid)
                queues.push(queue)
            if job.pod_group.status.phase == PodGroupPhase.PENDING:
                jobs_map.setdefault(
                    job.queue, job_queue_factory()).push(job)

        used = Resource()
        for node in ssn.nodes.values():
            used.add(node.used)
        idle = ssn.total_allocatable().clone().multi(
            self._overcommit_factor(ssn))
        try:
            idle.sub(used)
        except ValueError:
            idle = Resource()

        while not queues.empty():
            if idle.is_empty():
                break
            queue = queues.pop()
            jobs = jobs_map.get(queue.name)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()

            inqueue = False
            if not job.pod_group.spec.min_resources:
                inqueue = True
            else:
                min_req = Resource.from_resource_list(
                    job.pod_group.spec.min_resources)
                if ssn.job_enqueueable(job) and min_req.less_equal(idle):
                    try:
                        idle.sub(min_req)
                    except ValueError:
                        idle = Resource()
                    inqueue = True
            if inqueue:
                job.pod_group.status.phase = PodGroupPhase.INQUEUE
                # the flip happens on the session clone AFTER the snapshot
                # seam ran — an in-session delta the watch-fed ordering
                # ledger would never see (it changes allocate's
                # eligibility THIS cycle: Pending-phase jobs are skipped)
                oc = getattr(ssn, "order_cache", None)
                if oc is not None:
                    oc.feed_event("job", "session", job=job.uid)
            queues.push(queue)
