"""Reserve action (reference actions/reserve/reserve.go:27-50): lock nodes
for the elected target job until it becomes ready."""

from __future__ import annotations

from ..framework import Action
from ..utils.scheduler_helper import reservation


class ReserveAction(Action):
    def name(self) -> str:
        return "reserve"

    def execute(self, ssn) -> None:
        if reservation.target_job is None:
            return
        target = ssn.jobs.get(reservation.target_job.uid)
        if target is None:
            reservation.reset()
            return
        reservation.target_job = target
        if not target.ready():
            ssn.reserved_nodes()
        else:
            reservation.reset()
