"""Solver-mode machinery shared by preempt and reclaim: collect claimer
jobs and victims, flatten them, run ops.solve_evict on device, and replay
the result through the session's Statement/evict/pipeline boundary.

Mirrors the host loops' semantics (actions/preempt/preempt.go:41-262,
actions/reclaim/reclaim.go:40-192) with the documented frozen-order
deviations listed in ops/evict.py.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

import numpy as np

from ..api import TaskStatus
from ..models import PodGroupPhase
from ..utils import PriorityQueue

log = logging.getLogger(__name__)


def collect_claimer_jobs(ssn, require_not_pipelined: bool,
                         skip_overused: bool,
                         skip_jobs=()) -> List[Tuple[object, List]]:
    """(job, pending_tasks) pairs in queue -> job -> task order.

    require_not_pipelined: preempt only feeds jobs that are not yet
    JobPipelined (preempt.go:84-90); reclaim takes any starving job.
    skip_overused: reclaim skips overused queues (reclaim.go:57-58).
    skip_jobs: claimer uids routed through the host loop instead
    (host-only jobs — GPU sharing / affinity / PVC).
    """
    queues_pq = PriorityQueue(ssn.queue_order_fn)
    per_queue: Dict[str, PriorityQueue] = {}
    for job in ssn.jobs.values():
        if job.uid in skip_jobs:
            continue
        if job.pod_group.status.phase == PodGroupPhase.PENDING:
            continue
        vr = ssn.job_valid(job)
        if vr is not None and not vr.passed:
            continue
        queue = ssn.queues.get(job.queue)
        if queue is None:
            continue
        pending = job.task_status_index.get(TaskStatus.PENDING, {})
        if not any(not t.resreq.is_empty() for t in pending.values()):
            continue
        if require_not_pipelined and ssn.job_pipelined(job):
            continue
        if job.queue not in per_queue:
            per_queue[job.queue] = PriorityQueue(ssn.job_order_fn)
            queues_pq.push(queue)
        per_queue[job.queue].push(job)

    out = []
    while not queues_pq.empty():
        queue = queues_pq.pop()
        if skip_overused and ssn.overused(queue):
            continue
        jobs = per_queue.get(queue.name)
        oc = getattr(ssn, "order_cache", None)
        while jobs is not None and not jobs.empty():
            job = jobs.pop()
            # version-gated reuse of the OrderCache's sorted pending
            # list: same filter (non-best-effort Pending) and the same
            # total order (task_order_fn == the full task key), so a job
            # unchanged since allocate's last keyed cycle skips the
            # per-task push/pop sort here
            tasks = oc.pending_tasks(ssn, job) if oc is not None else None
            if tasks is None:
                tq = PriorityQueue(ssn.task_order_fn)
                for t in job.task_status_index.get(
                        TaskStatus.PENDING, {}).values():
                    if not t.resreq.is_empty():
                        tq.push(t)
                tasks = []
                while not tq.empty():
                    tasks.append(tq.pop())
            if tasks:
                out.append((job, tasks))
    return out


def collect_victims(ssn, nodes_list) -> List:
    """Running, non-best-effort tasks of known jobs, grouped by node in the
    node-index order of the flatten, cheapest-first within each node (the
    order the host loops pop their victim priority queue,
    preempt.go:219-228). Clones, like the host paths, so replay decisions
    never mutate session state early."""
    victims = []
    for ni in nodes_list:
        pq = PriorityQueue(lambda l, r: not ssn.task_order_fn(l, r))
        for t in ni.tasks.values():
            if t.status != TaskStatus.RUNNING or t.resreq.is_empty():
                continue
            if t.job not in ssn.jobs:
                continue
            pq.push(t.clone())
        while not pq.empty():
            victims.append(pq.pop())
    return victims


def build_victim_arrays(ssn, arr, victims, job_order, mode: str) -> Dict:
    """Victim device arrays + per-claimer-job eligibility masks.

    Eligibility = queue scoping (same queue & different job for preempt;
    other reclaimable queues for reclaim) intersected with the session's
    tiered Preemptable/Reclaimable verdicts, evaluated once per claimer job
    (the plugin fns read the claimer's job, not the individual task)."""
    from ..ops.arrays import bucket

    node_index = {n.name: i for i, n in enumerate(arr.nodes_list)}
    R = arr.R
    J = arr.job_min.shape[0]
    V = bucket(max(len(victims), 1))
    v_req = np.zeros((V, R), dtype=np.float32)
    v_node = np.zeros(V, dtype=np.int32)
    v_valid = np.zeros(V, dtype=bool)
    for i, t in enumerate(victims):
        v_req[i] = t.resreq.to_vector(arr.vocab)
        v_node[i] = node_index[t.node_name]
        v_valid[i] = True

    elig = np.zeros((J, V), dtype=bool)
    need = np.zeros(J, dtype=np.int32)
    for j, (job, tasks) in enumerate(job_order):
        if mode == "preempt":
            cands = [t for t in victims
                     if ssn.jobs[t.job].queue == job.queue
                     and t.job != job.uid]
            allowed = {v.uid for v in ssn.preemptable(tasks[0], cands)}
            # pipelines still needed for JobPipelined (job_info.go:373-377)
            need[j] = max(0, job.min_available
                          - (job.ready_task_num() + job.waiting_task_num()))
        else:
            cands = []
            for t in victims:
                vq = ssn.queues.get(ssn.jobs[t.job].queue)
                if (ssn.jobs[t.job].queue != job.queue
                        and vq is not None and vq.reclaimable):
                    cands.append(t)
            allowed = {v.uid for v in ssn.reclaimable(tasks[0], cands)}
            need[j] = len(tasks)  # uncapped (reclaim has no gang stop)
        for i, t in enumerate(victims):
            elig[j, i] = t.uid in allowed
    return {"v_req": v_req, "v_node": v_node, "v_valid": v_valid,
            "elig": elig, "job_need": need}


def _evictions_by_job(evicted_by: np.ndarray) -> Dict[int, List[int]]:
    """claimer job index -> victim indices in victim-sorted
    (cheapest-first) order."""
    out: Dict[int, List[int]] = {}
    for vi, ji in enumerate(evicted_by):
        if ji >= 0:
            out.setdefault(int(ji), []).append(vi)
    return out


def _uniform_job_arrays(arr, job_order):
    """(job_req, job_acct [J,R], job_count [J]) when every claimer job's
    pending tasks share one fit request, one accounting request, and one
    signature, else None (the per-job closed-form kernel requires
    uniformity)."""
    J = arr.job_min.shape[0]
    job_req = np.zeros((J, arr.R), dtype=np.float32)
    job_acct = np.zeros((J, arr.R), dtype=np.float32)
    job_count = np.zeros(J, dtype=np.int32)
    off = 0
    for j, (_job, tasks) in enumerate(job_order):
        k = len(tasks)
        fit = arr.task_init_req[off:off + k]
        acct = arr.task_req[off:off + k]
        sigs = arr.task_sig[off:off + k]
        if k > 1 and (not (fit == fit[0]).all()
                      or not (acct == acct[0]).all()
                      or not (sigs == sigs[0]).all()):
            return None
        job_req[j] = fit[0]
        job_acct[j] = acct[0]
        job_count[j] = k
        off += k
    return job_req, job_acct, job_count


def run_evict_solver(ssn, mode: str, skip_jobs=()):
    """Flatten claimers + victims, solve on device, replay. Returns the
    claimer jobs processed (the host loops' under_request set — preempt's
    intra-job phase must run on exactly these), [] when there was nothing
    to do, or None when the device path is unavailable (circuit breaker
    open, or the solve itself failed) — the caller then degrades to its
    host loop for this cycle."""
    from ..ops import flatten_snapshot
    from ..ops.evict import solve_evict
    from ..resilience import faults
    from .allocate import build_score_inputs

    breaker = getattr(ssn, "breaker", None)
    if breaker is not None and not breaker.allow():
        breaker.count_fallback()
        return None  # circuit open: host loop covers this cycle
    preempt = mode == "preempt"
    job_order = collect_claimer_jobs(
        ssn, require_not_pipelined=preempt, skip_overused=not preempt,
        skip_jobs=skip_jobs)
    if not job_order:
        return []
    tasks_in_order = [t for _, tasks in job_order for t in tasks]
    arr = flatten_snapshot(
        {j.uid: j for j, _ in job_order}, ssn.nodes, tasks_in_order,
        queues=ssn.queues,
        cache=getattr(ssn, "evict_flatten_caches", {}).get(mode),
        grouped=job_order)
    victims = collect_victims(ssn, arr.nodes_list)
    if not victims:
        return [j for j, _ in job_order]
    varrays = build_victim_arrays(ssn, arr, victims, job_order, mode)
    params, families = build_score_inputs(ssn, arr)

    # the closed-form kernel is preempt-only: reclaim's per-claimer victim
    # coverage rule is not a per-node divisibility (see solve_evict_uniform)
    uniform = _uniform_job_arrays(arr, job_order) if preempt else None
    if uniform is not None:
        (varrays["job_req"], varrays["job_acct"],
         varrays["job_count"]) = uniform
    vnp = {k: np.asarray(v) for k, v in varrays.items()}
    sidecar = getattr(ssn, "sidecar", None)
    try:
        # breaker scope: a throwing evict dispatch/collect (or an injected
        # fault) counts one consecutive device failure; the caller's host
        # loop covers this cycle
        faults.fire("evict_dispatch")
        if sidecar is not None:
            # process boundary: evict solves ship to the solver process
            # too (job_req in the victim dict selects the fast path)
            assigned, evicted_by = sidecar.solve_evict(
                arr.device_dict(), vnp, params, score_families=families,
                require_freed_covers=not preempt,
                allow_revert=preempt, stop_at_need=preempt)
        else:
            if uniform is not None:
                # gang fast path: one solve step per JOB
                # (solve_evict_uniform)
                from ..ops.evict import solve_evict_uniform
                res = solve_evict_uniform(
                    arr.device_dict(), vnp, params,
                    score_families=families,
                    require_freed_covers=False, stop_at_need=True)
            else:
                res = solve_evict(
                    arr.device_dict(), vnp, params,
                    score_families=families,
                    require_freed_covers=not preempt,
                    allow_revert=preempt, stop_at_need=preempt)
            from ..ops.evict import decode_evict_compact
            try:
                # one int16 readback carries both outputs (remote wire)
                assigned, evicted_by = decode_evict_compact(
                    res.compact, arr.task_init_req.shape[0])
            except ValueError:  # >32k nodes/jobs: indices overflow packing
                assigned = np.asarray(res.assigned)
                evicted_by = np.asarray(res.evicted_by)
    except Exception:
        log.exception("%s device solve failed; degrading to the host "
                      "loop for this cycle", mode)
        if breaker is not None:
            breaker.record_failure()
        return None
    if breaker is not None:
        breaker.record_success()
    by_job = _evictions_by_job(evicted_by)

    from ..metrics import metrics
    idx = 0
    for j, (job, tasks) in enumerate(job_order):
        stmt = ssn.statement() if preempt else None
        evs = by_job.get(j, ())
        if evs:
            # post-solve validation (ADVICE r2 #2): the solve froze plugin
            # verdicts at collection time, so several claimers can jointly
            # evict more of one victim job than per-placement re-evaluated
            # verdicts allow (share-bounded plugins like DRF). Re-ask the
            # session NOW — prior jobs' evictions are already applied. If
            # the live verdict retracts ANY planned victim, skip this
            # claimer's whole replay (evict nothing, pipeline nothing):
            # its placements were computed against capacity those victims
            # would have freed, so partially replaying would pipeline onto
            # capacity that never frees. The job retries next cycle with
            # fresh verdicts.
            live = [victims[vi] for vi in evs]
            verdict = (ssn.preemptable if preempt else ssn.reclaimable)(
                tasks[0], live)
            allowed_now = {v.uid for v in verdict}
            if any(victims[vi].uid not in allowed_now for vi in evs):
                log.info("%s: live plugin verdicts retracted victims for "
                         "%s; deferring the job to the next cycle",
                         mode, job.uid)
                idx += len(tasks)
                continue
        # the job's evictions land first (cheapest-first order), then its
        # claimers pipeline — one Statement per job like the host loop's
        # per-preemptor statements rolled up. Per-victim try: one failing
        # eviction must not skip the rest (the pipelines would otherwise
        # land on capacity that was never freed)
        for vi in evs:
            try:
                if preempt:
                    stmt.evict(victims[vi], "preempt")
                else:
                    ssn.evict(victims[vi], "reclaim")
            except (KeyError, ValueError):
                log.exception("%s eviction replay failed for %s",
                              mode, victims[vi].key)
        for task in tasks:
            t_idx = idx
            idx += 1
            node_idx = int(assigned[t_idx])
            if node_idx < 0:
                continue
            node_name = arr.nodes_list[node_idx].name
            try:
                if preempt:
                    stmt.pipeline(task, node_name)
                    metrics.preemption_attempts.inc()
                else:
                    ssn.pipeline(task, node_name)
            except (KeyError, ValueError):
                log.exception("%s replay failed for %s", mode, task.key)
        if preempt:
            metrics.preemption_victims.set(len(evs))
            if ssn.job_pipelined(job):
                stmt.commit()
            else:
                stmt.discard()
    return [j for j, _ in job_order]
