"""Actions (reference pkg/scheduler/actions)."""

from ..framework import register_action
from .allocate import AllocateAction  # noqa: F401
from .backfill import BackfillAction  # noqa: F401
from .enqueue import EnqueueAction  # noqa: F401


def register_all() -> None:
    register_action(EnqueueAction())
    register_action(AllocateAction())
    register_action(BackfillAction())
    for name in ("preempt", "reclaim", "elect", "reserve"):
        try:
            import importlib
            mod = importlib.import_module(f".{name}", __package__)
            register_action(getattr(mod, f"{name.capitalize()}Action")())
        except (ImportError, AttributeError):
            pass
    # the global rescheduler lives in its own subsystem package
    # (volcano_tpu.reschedule) but registers like any other action
    from ..reschedule import RescheduleAction
    register_action(RescheduleAction())


register_all()
