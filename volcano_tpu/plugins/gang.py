"""Gang plugin (reference plugins/gang/gang.go:50-194)."""

from __future__ import annotations

from ..api import TaskStatus
from ..framework import Plugin, ValidateResult
from ..metrics import metrics
from ..models import (
    NOT_ENOUGH_PODS_REASON, NOT_ENOUGH_RESOURCES_REASON,
    POD_GROUP_READY_REASON, POD_GROUP_SCHEDULED_TYPE,
    POD_GROUP_UNSCHEDULABLE_TYPE, PodGroupCondition,
)
from ..api.unschedule_info import FitErrors


class GangPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "gang"

    def on_session_open(self, ssn) -> None:
        def valid_job_fn(job):
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    False, NOT_ENOUGH_PODS_REASON,
                    f"Not enough valid tasks for gang-scheduling, "
                    f"valid: {vtn}, min: {job.min_available}")
            return None

        ssn.add_job_valid_fn(self.name(), valid_job_fn)

        def preemptable_fn(preemptor, preemptees):
            """Victims only from jobs of strictly lower priority."""
            p_job = ssn.jobs.get(preemptor.job)
            victims = []
            for preemptee in preemptees:
                job = ssn.jobs.get(preemptee.job)
                if p_job is not None and job is not None \
                        and p_job.priority > job.priority:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name(), preemptable_fn)
        ssn.add_reclaimable_fn(self.name(), preemptable_fn)

        def job_order_fn(l, r):
            """Unready jobs sort first."""
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready and r_ready:
                return 0
            if l_ready:
                return 1
            if r_ready:
                return -1
            return 0

        ssn.add_job_order_fn(self.name(), job_order_fn)
        # key form of the comparator: unready (False) sorts before ready
        ssn.add_order_key_fn("job_order_fns", self.name(),
                             lambda j: j.ready())
        ssn.add_job_ready_fn(self.name(), lambda job: job.ready())
        ssn.add_job_pipelined_fn(self.name(), lambda job: job.pipelined())

    def on_session_close(self, ssn) -> None:
        unschedulable_count = 0
        for job in ssn.jobs.values():
            if job.pod_group is None:
                continue
            if not job.ready():
                unready = job.min_available - job.ready_task_num()
                msg = (f"{unready}/{len(job.tasks)} tasks in gang "
                       f"unschedulable: {job.fit_message()}")
                unschedulable_count += 1
                metrics.unschedule_task_count.set(
                    max(unready, 0), {"job_id": job.name})
                metrics.job_retry_counts.inc(labels={"job_id": job.name})
                ssn.update_pod_group_condition(job, PodGroupCondition(
                    type=POD_GROUP_UNSCHEDULABLE_TYPE, status="True",
                    transition_id=ssn.uid,
                    reason=NOT_ENOUGH_RESOURCES_REASON, message=msg))
                # allocated tasks follow the job fit error
                for task in job.task_status_index.get(
                        TaskStatus.ALLOCATED, {}).values():
                    if task.key not in job.nodes_fit_errors:
                        fe = FitErrors()
                        fe.set_error(msg)
                        job.nodes_fit_errors[task.key] = fe
            else:
                # steady-state fast path: when the identical Scheduled
                # condition is already posted, skip the re-post — only
                # transition_id/time would change, which the status diff
                # rule (PodGroupStatus.fingerprint) treats as
                # insignificant anyway. At 1k ready jobs per cycle the
                # per-job condition object churn was measurable.
                if not any(c.type == POD_GROUP_SCHEDULED_TYPE
                           and c.status == "True"
                           and c.reason == POD_GROUP_READY_REASON
                           and not c.message
                           for c in job.pod_group.status.conditions):
                    ssn.update_pod_group_condition(job, PodGroupCondition(
                        type=POD_GROUP_SCHEDULED_TYPE, status="True",
                        transition_id=ssn.uid,
                        reason=POD_GROUP_READY_REASON))
        metrics.unschedule_job_count.set(unschedulable_count)
