"""Binpack plugin (reference plugins/binpack/binpack.go:111-260).

Best-fit scoring: score = 100 * sum_r w_r * (used_r + req_r) / alloc_r / sum_w,
scaled by the plugin weight. On the TPU path this sets the binpack score
family weights (the kernel evaluates it as a [T,R]x[R,N] matmul); the host
node-order fn provides identical per-pair scoring for non-solver paths.
"""

from __future__ import annotations

import numpy as np

from ..framework import Arguments, Plugin


class BinpackPlugin(Plugin):
    def __init__(self, arguments=None):
        args = Arguments(arguments or {})
        self.weight = args.get_int("binpack.weight", 1)
        self.cpu_weight = args.get_int("binpack.cpu", 1)
        self.memory_weight = args.get_int("binpack.memory", 1)
        # custom scalar resources: "binpack.resources": "nvidia.com/gpu,..."
        # with per-resource "binpack.resources.nvidia.com/gpu": weight
        self.resource_weights = {}
        raw = args.get("binpack.resources", "")
        for name in str(raw).split(","):
            name = name.strip()
            if name:
                self.resource_weights[name] = args.get_int(
                    f"binpack.resources.{name}", 1)

    def name(self) -> str:
        return "binpack"

    def _weights_vector(self, vocab) -> np.ndarray:
        w = np.zeros(len(vocab), dtype=np.float32)
        w[0] = self.cpu_weight
        w[1] = self.memory_weight
        for name, wt in self.resource_weights.items():
            idx = vocab.index(name)
            if idx is not None:
                w[idx] = wt
        return w

    def on_session_open(self, ssn) -> None:
        ssn.score_params.binpack_weight = float(self.weight)
        ssn.solver_options["binpack_vocab_weights"] = self._weights_vector
        ssn.solver_options.setdefault("herd_mode", "pack")

        def node_order_fn(task, node) -> float:
            """Host-path equivalent of the kernel's binpack family."""
            names = ["cpu", "memory"] + list(self.resource_weights)
            score, wsum = 0.0, 0.0
            for name in names:
                if name == "cpu":
                    w, used, req, alloc = (self.cpu_weight,
                                           node.used.milli_cpu,
                                           task.init_resreq.milli_cpu,
                                           node.allocatable.milli_cpu)
                elif name == "memory":
                    w, used, req, alloc = (self.memory_weight,
                                           node.used.memory,
                                           task.init_resreq.memory,
                                           node.allocatable.memory)
                else:
                    w = self.resource_weights[name]
                    used = node.used.scalars.get(name, 0.0)
                    req = task.init_resreq.scalars.get(name, 0.0)
                    alloc = node.allocatable.scalars.get(name, 0.0)
                wsum += w
                if alloc > 0:
                    score += w * (used + req) * 100.0 / alloc
            if wsum <= 0:
                return 0.0
            return self.weight * score / wsum

        ssn.add_node_order_fn(self.name(), node_order_fn)

    def on_session_close(self, ssn) -> None:
        pass
