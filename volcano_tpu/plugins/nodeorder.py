"""Nodeorder plugin (reference plugins/nodeorder/nodeorder.go:100-276).

Wraps the k8s scorers the reference uses: least-requested,
balanced-allocation, most-requested, node-affinity and taint-toleration
preferences. Scalar weights feed the kernel's score families; the host
node-order fn mirrors them per pair.
"""

from __future__ import annotations

from ..framework import Arguments, Plugin
from ..ops.arrays import taint_tolerated


class NodeOrderPlugin(Plugin):
    def __init__(self, arguments=None):
        args = Arguments(arguments or {})
        self.least_requested = args.get_int("leastrequested.weight", 1)
        self.most_requested = args.get_int("mostrequested.weight", 0)
        self.balanced = args.get_int("balancedresource.weight", 1)
        self.node_affinity = args.get_int("nodeaffinity.weight", 1)
        self.taint_toleration = args.get_int("tainttoleration.weight", 1)
        self.pod_affinity = args.get_int("podaffinity.weight", 1)

    def name(self) -> str:
        return "nodeorder"

    def on_session_open(self, ssn) -> None:
        ssn.score_params.least_req_weight = float(self.least_requested)
        ssn.score_params.most_req_weight = float(self.most_requested)
        ssn.score_params.balanced_weight = float(self.balanced)
        if self.most_requested <= max(self.least_requested, self.balanced):
            ssn.solver_options.setdefault("herd_mode", "spread")

        def node_order_fn(task, node) -> float:
            alloc_cpu = node.allocatable.milli_cpu or 1.0
            alloc_mem = node.allocatable.memory or 1.0
            fc = min(max((node.used.milli_cpu + task.init_resreq.milli_cpu)
                         / alloc_cpu, 0.0), 1.0)
            fm = min(max((node.used.memory + task.init_resreq.memory)
                         / alloc_mem, 0.0), 1.0)
            least = (1.0 - (fc + fm) / 2.0) * 100.0
            most = ((fc + fm) / 2.0) * 100.0
            balanced = (1.0 - abs(fc - fm)) * 100.0
            # preferredDuringScheduling node affinity terms
            affinity_score = 0.0
            pod = task.pod
            if pod.affinity and node.node is not None:
                na = (pod.affinity.get("nodeAffinity") or {})
                for pref in na.get(
                        "preferredDuringSchedulingIgnoredDuringExecution", []):
                    weight = pref.get("weight", 0)
                    sel = (pref.get("preference") or {}).get("matchLabels", {})
                    labels = node.node.labels or {}
                    if all(labels.get(k) == v for k, v in sel.items()):
                        affinity_score += weight
            # taint-toleration preference: fewer intolerable
            # PreferNoSchedule taints score higher (k8s tainttoleration
            # scorer, per-node form of its count-and-normalize reduce)
            taint_score = 0.0
            if node.node is not None:
                intolerable = 0
                for taint in node.node.taints or []:
                    if taint.get("effect") != "PreferNoSchedule":
                        continue
                    if not taint_tolerated(taint, pod.tolerations or []):
                        intolerable += 1
                taint_score = 100.0 / (1.0 + intolerable)
            # preferred inter-pod (anti-)affinity: weight per matching term
            # against pods already on the node
            pa_score = 0.0
            if pod.affinity:
                on_node = [t.pod for t in node.tasks.values()]
                for kind, sign in (("podAffinity", 1.0),
                                   ("podAntiAffinity", -1.0)):
                    spec = (pod.affinity.get(kind) or {})
                    for pref in spec.get(
                            "preferredDuringSchedulingIgnoredDuringExecution",
                            []):
                        weight = pref.get("weight", 0)
                        term = pref.get("podAffinityTerm") or {}
                        sel = (term.get("labelSelector") or {}).get(
                            "matchLabels", {})
                        if not sel:
                            # matchExpressions-only selectors are not
                            # evaluated here; an empty matchLabels must not
                            # match every pod
                            continue
                        # k8s scopes the term to its namespaces list, or
                        # the incoming pod's namespace by default, and
                        # scores weight PER matching existing pod (a node
                        # holding 3 matches outranks one holding 1)
                        namespaces = set(term.get("namespaces")
                                         or [pod.namespace])
                        matches = sum(
                            1 for p in on_node
                            if p.namespace in namespaces
                            and all((p.labels or {}).get(k) == v
                                    for k, v in sel.items()))
                        pa_score += sign * weight * matches
            return (self.least_requested * least
                    + self.most_requested * most
                    + self.balanced * balanced
                    + self.node_affinity * affinity_score
                    + self.taint_toleration * taint_score
                    + self.pod_affinity * pa_score)

        ssn.add_node_order_fn(self.name(), node_order_fn)

    def on_session_close(self, ssn) -> None:
        pass
