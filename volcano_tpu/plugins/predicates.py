"""Predicates plugin (reference plugins/predicates/predicates.go:100-255).

Wraps the k8s filter set the reference uses: NodeUnschedulable, NodeAffinity
(+ nodeSelector), TaintToleration, NodePorts, pod-count, and (optionally)
InterPodAffinity. Two forms:

- host predicate fn registered on the session (exact per-pair semantics for
  backfill/preempt/reclaim paths and tests);
- for the allocate solver, the same constraints are flattened into
  sig_masks by ops.flatten_snapshot (signature gather), so the plugin's job
  there is only to declare that the mask set is active.
"""

from __future__ import annotations

import logging

from ..api import FitError, TaskStatus
from ..api.device_info import (
    add_gpu_index, get_gpu_index, gpu_resource_of_pod, predicate_gpu,
    remove_gpu_index,
)
from ..api.unschedule_info import (
    GPU_SHARING_FAILED, NODE_AFFINITY_FAILED, NODE_PORTS_FAILED,
    NODE_UNSCHEDULABLE, POD_AFFINITY_FAILED, POD_COUNT_FAILED,
    PVC_NOT_FOUND, TAINT_FAILED, VOLUME_BINDING_FAILED,
)
from ..framework import Plugin
from ..framework.event import EventHandler
from ..ops.arrays import (
    _match_node_selector, _node_affinity_match, _tolerates,
)

logger = logging.getLogger(__name__)


class PredicateError(Exception):
    def __init__(self, fit_error: FitError):
        super().__init__(fit_error.error())
        self.fit_error = fit_error


def _has_required_pod_affinity(pod) -> bool:
    """True when the pod carries requiredDuringScheduling inter-pod
    (anti-)affinity terms — feasibility then depends on in-flight placements,
    which only the sequential host loop tracks."""
    aff = pod.affinity or {}
    for kind in ("podAffinity", "podAntiAffinity"):
        if (aff.get(kind) or {}).get(
                "requiredDuringSchedulingIgnoredDuringExecution"):
            return True
    return False


def _pod_affinity_ok(pod, node, tasks_on_node) -> bool:
    """Minimal inter-pod affinity/anti-affinity: requiredDuringScheduling
    terms with matchLabels over topologyKey kubernetes.io/hostname."""
    aff = pod.affinity or {}
    for kind, want in (("podAffinity", True), ("podAntiAffinity", False)):
        spec = aff.get(kind) or {}
        for term in spec.get("requiredDuringSchedulingIgnoredDuringExecution", []):
            sel = (term.get("labelSelector") or {}).get("matchLabels", {})
            matched = any(
                all((t.pod.labels or {}).get(k) == v for k, v in sel.items())
                for t in tasks_on_node)
            if want and not matched:
                return False
            if not want and matched:
                return False
    return True


class PredicatesPlugin(Plugin):
    def __init__(self, arguments=None):
        from ..framework import Arguments

        self.arguments = arguments or {}
        # predicate.GPUSharingEnable (predicates.go:100-133)
        args = (self.arguments if isinstance(self.arguments, Arguments)
                else Arguments(self.arguments))
        self.gpu_sharing = args.get_bool("predicate.GPUSharingEnable", False)

    def name(self) -> str:
        return "predicates"

    def on_session_open(self, ssn) -> None:
        ssn.solver_options["predicates"] = True
        # The batched kernel's feasibility masks are precomputed per node and
        # cannot see in-flight same-session placements, so required inter-pod
        # (anti-)affinity must run the sequential host loop. Scoped per job
        # (one affine pod must not downgrade the whole cluster's cycle):
        # allocate solves the other jobs on device and routes only these
        # through the host loop. Mirrors predicates.go:171-237
        # InterPodAffinity being a full k8s filter in the reference.
        # Only pending tasks matter: _pod_affinity_ok evaluates the incoming
        # pod's terms, never existing pods' (no anti-affinity symmetry), so a
        # long-Running affine pod must not downgrade any cycle to host mode.
        # PVC-carrying jobs join the same host routing: the kernel's sig
        # masks don't know claim node pins, and a wrong-node replay would
        # silently discard the gang every cycle (claim pins also depend on
        # in-flight same-session assumptions, which only the host loop's
        # volume-binding predicate tracks).
        def _has_claim(pod):
            return any((v.get("persistentVolumeClaim") or {}).get(
                "claimName") for v in getattr(pod, "volumes", None) or [])

        host_only = {
            job.uid for job in ssn.jobs.values()
            if any(_has_required_pod_affinity(t.pod) or _has_claim(t.pod)
                   for t in job.task_status_index.get(
                       TaskStatus.PENDING, {}).values())}
        if host_only:
            ssn.solver_options["host_only_jobs"] = host_only
        if self.gpu_sharing:
            # per-card feasibility depends on in-flight card assignments,
            # which only the host loop tracks — but that's a property of
            # GPU-REQUESTING jobs, not the cycle: route exactly those jobs
            # through the host loop (the same per-job mechanism as
            # affinity/PVC above) and keep everything else on the device
            # path. One GPU job must not downgrade a 10k-pod cycle.
            gpu_jobs = {
                job.uid for job in ssn.jobs.values()
                if any(gpu_resource_of_pod(t.pod) > 0
                       for t in job.task_status_index.get(
                           TaskStatus.PENDING, {}).values())}
            if gpu_jobs:
                host_only = set(ssn.solver_options.get("host_only_jobs")
                                or ()) | gpu_jobs
                ssn.solver_options["host_only_jobs"] = host_only
            # evict-then-discard undo must restore the card the pod actually
            # occupies, not re-run first-fit: uid -> (node_name, card id)
            released_cards = {}

            def on_allocate(event):
                """Pick a card, annotate the pod, join its pod_map
                (predicates.go:117-133 AllocateFunc)."""
                task = event.task
                pod = task.pod
                if gpu_resource_of_pod(pod) <= 0:
                    return
                node_info = ssn.nodes.get(task.node_name)
                if node_info is None:
                    return
                restored = released_cards.pop(pod.uid, None)
                if restored is not None and restored[0] == task.node_name:
                    dev_id = restored[1]
                else:
                    dev_id = predicate_gpu(pod, node_info)
                if dev_id < 0:
                    # node-level gpu memory was just accounted for this task
                    # but no card fits: surface the inconsistency instead of
                    # silently leaving the pod without a card assignment
                    # (predicates.go:117-133 logs the allocate error)
                    logger.error(
                        "gpu allocate: no card on node <%s> fits pod <%s/%s> "
                        "(node accounting and card assignment now disagree)",
                        task.node_name, pod.namespace, pod.name)
                    return
                add_gpu_index(pod, dev_id)
                dev = node_info.gpu_devices.get(dev_id)
                if dev is not None:
                    dev.pod_map[pod.uid] = pod

            def on_deallocate(event):
                """Free the card on statement undo / eviction
                (predicates.go:145-160 DeallocateFunc)."""
                task = event.task
                pod = task.pod
                if gpu_resource_of_pod(pod) <= 0:
                    return
                node_info = ssn.nodes.get(task.node_name)
                dev_id = get_gpu_index(pod)
                if node_info is not None:
                    if dev_id >= 0:
                        released_cards[pod.uid] = (task.node_name, dev_id)
                    dev = node_info.gpu_devices.get(dev_id)
                    if dev is not None:
                        dev.pod_map.pop(pod.uid, None)
                remove_gpu_index(pod)

            ssn.add_event_handler(EventHandler(
                allocate_func=on_allocate, deallocate_func=on_deallocate))

        def predicate_fn(task, node_info):
            node = node_info.node
            pod = task.pod
            reasons = []
            if node is None or not node_info.ready:
                reasons.append(NODE_UNSCHEDULABLE)
            else:
                max_tasks = node_info.allocatable.max_task_num
                if max_tasks and len(node_info.tasks) >= max_tasks:
                    reasons.append(POD_COUNT_FAILED)
                if not _match_node_selector(pod.node_selector or {}, node) \
                        or not _node_affinity_match(pod.affinity, node):
                    reasons.append(NODE_AFFINITY_FAILED)
                if not _tolerates(pod.tolerations, node):
                    reasons.append(TAINT_FAILED)
                if pod.ports():
                    taken = set()
                    for other in node_info.tasks.values():
                        taken.update(other.pod.ports())
                    if set(pod.ports()) & taken:
                        reasons.append(NODE_PORTS_FAILED)
                if pod.affinity and not _pod_affinity_ok(
                        pod, node, list(node_info.tasks.values())):
                    reasons.append(POD_AFFINITY_FAILED)
                if self.gpu_sharing and gpu_resource_of_pod(pod) > 0 \
                        and predicate_gpu(pod, node_info) < 0:
                    # no single card has enough idle memory (gpu.go:27-55)
                    reasons.append(GPU_SHARING_FAILED)
                if getattr(pod, "volumes", None):
                    # volume-binding filter: a claim pinned to another node
                    # excludes this one (the k8s CheckVolumeBinding
                    # predicate the reference wires in)
                    vb = getattr(getattr(ssn, "cache", None),
                                 "volume_binder", None)
                    if getattr(vb, "missing_claims", lambda p: ())(pod):
                        reasons.append(PVC_NOT_FOUND)
                    elif getattr(vb, "node_ok", None) is not None \
                            and not vb.node_ok(pod, node.name):
                        reasons.append(VOLUME_BINDING_FAILED)
            if reasons:
                raise PredicateError(FitError(task, node_info.name, reasons))

        ssn.add_predicate_fn(self.name(), predicate_fn)

    def on_session_close(self, ssn) -> None:
        pass
