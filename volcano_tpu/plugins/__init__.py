"""Scheduling plugins + factory (reference pkg/scheduler/plugins)."""

from ..framework import register_plugin_builder
from .binpack import BinpackPlugin  # noqa: F401
from .conformance import ConformancePlugin  # noqa: F401
from .gang import GangPlugin  # noqa: F401
from .nodeorder import NodeOrderPlugin  # noqa: F401
from .predicates import PredicateError, PredicatesPlugin  # noqa: F401
from .priority import PriorityPlugin  # noqa: F401


def register_all() -> None:
    """plugins/factory.go:32-46."""
    register_plugin_builder("gang", GangPlugin)
    register_plugin_builder("priority", PriorityPlugin)
    register_plugin_builder("predicates", PredicatesPlugin)
    register_plugin_builder("nodeorder", NodeOrderPlugin)
    register_plugin_builder("binpack", BinpackPlugin)
    register_plugin_builder("conformance", ConformancePlugin)
    try:
        from .drf import DRFPlugin
        register_plugin_builder("drf", DRFPlugin)
    except ImportError:
        pass
    try:
        from .proportion import ProportionPlugin
        register_plugin_builder("proportion", ProportionPlugin)
    except ImportError:
        pass
    try:
        from .reservation import ReservationPlugin
        register_plugin_builder("reservation", ReservationPlugin)
    except ImportError:
        pass


register_all()
