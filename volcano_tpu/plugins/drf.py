"""DRF plugin: dominant-resource fairness (reference plugins/drf/drf.go:41-663).

Three modes, all reimplemented faithfully:
- plain: per-job dominant share ordering + share-based preemption;
- namespace-weighted: namespace order by share/weight, namespace-aware
  preemption policy;
- hierarchical (hdrf): queue-path tree with weighted shares and
  saturation-aware scaling; queue order + reclaimable by hierarchical
  comparison. Incompatible with the proportion plugin (conf loader rejects).

Shares are maintained incrementally through session event handlers, exactly
like the reference, so they stay consistent with every allocate/evict the
solver replays through the Statement boundary.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

from ..api import Resource, TaskStatus
from ..framework import Arguments, EventHandler, Plugin
from ..metrics import metrics

SHARE_DELTA = 0.000001


def share(l: float, r: float) -> float:
    if r == 0:
        return 0.0 if l == 0 else 1.0
    return l / r


class _DrfAttr:
    __slots__ = ("share", "dominant_resource", "allocated")

    def __init__(self, allocated: Optional[Resource] = None):
        self.share = 0.0
        self.dominant_resource = ""
        self.allocated = allocated if allocated is not None else Resource()


class _HNode:
    """Hierarchical-tree node (drf.go:41-91)."""

    def __init__(self, hierarchy: str, weight: float = 1.0,
                 attr: Optional[_DrfAttr] = None, request=None,
                 children: Optional[dict] = None):
        self.parent = None
        self.attr = attr if attr is not None else _DrfAttr()
        self.request = request if request is not None else Resource()
        self.weight = weight
        self.saturated = False
        self.hierarchy = hierarchy
        self.children: Optional[Dict[str, _HNode]] = children

    def clone(self, parent=None) -> "_HNode":
        n = _HNode(self.hierarchy, self.weight)
        n.parent = parent
        n.attr = _DrfAttr(self.attr.allocated.clone())
        n.attr.share = self.attr.share
        n.attr.dominant_resource = self.attr.dominant_resource
        n.request = self.request.clone()
        n.saturated = self.saturated
        if self.children is not None:
            n.children = {c.hierarchy: c.clone(n)
                          for c in self.children.values()}
        return n


def _resource_saturated(allocated: Resource, job_request: Resource,
                        demanding: Dict[str, bool]) -> bool:
    for rn in allocated.resource_names():
        alloc, req = allocated.get(rn), job_request.get(rn)
        if alloc != 0 and req != 0 and alloc >= req:
            return True
        if not demanding.get(rn, False) and req != 0:
            return True
    return False


class DRFPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = Arguments(arguments or {})
        self.total_resource = Resource()
        self.total_allocated = Resource()
        self.job_attrs: Dict[str, _DrfAttr] = {}
        self.namespace_opts: Dict[str, _DrfAttr] = {}
        self.hierarchical_root = _HNode("root", 1.0, children={})

    def name(self) -> str:
        return "drf"

    # -- mode flags (plugin option enables) ---------------------------------

    def _hierarchy_enabled(self, ssn) -> bool:
        for tier in ssn.tiers:
            for opt in tier.plugins:
                if opt.name == self.name():
                    return bool(opt.arguments.get("drf.enableHierarchy")) \
                        or bool(getattr(opt, "enabled_hierarchy", False))
        return False

    def _namespace_order_enabled(self, ssn) -> bool:
        for tier in ssn.tiers:
            for opt in tier.plugins:
                if opt.name == self.name():
                    return bool(opt.enabled_namespace_order)
        return False

    # -- share math ---------------------------------------------------------

    def calculate_share(self, allocated: Resource, total: Resource):
        res, dominant = 0.0, ""
        for rn in total.resource_names():
            s = share(allocated.get(rn), total.get(rn))
            if s > res:
                res, dominant = s, rn
        return dominant, res

    def _update_share(self, attr: _DrfAttr) -> None:
        attr.dominant_resource, attr.share = self.calculate_share(
            attr.allocated, self.total_resource)

    def _update_job_share(self, job_ns, job_name, attr) -> None:
        self._update_share(attr)
        metrics.job_share.set(attr.share,
                              {"job_ns": job_ns, "job_id": job_name})

    def _update_namespace_share(self, ns, attr) -> None:
        self._update_share(attr)
        metrics.namespace_share.set(attr.share, {"namespace_name": ns})

    # -- hierarchy ----------------------------------------------------------

    def _build_hierarchy(self, root: _HNode, job, attr: _DrfAttr,
                         hierarchy: str, weights: str) -> None:
        inode = root
        paths = hierarchy.split("/")
        wparts = weights.split("/")
        for i in range(1, len(paths)):
            child = (inode.children or {}).get(paths[i])
            if child is None:
                try:
                    fweight = float(wparts[i])
                except (IndexError, ValueError):
                    fweight = 1.0
                fweight = max(fweight, 1.0)
                child = _HNode(paths[i], fweight, children={})
                child.parent = inode
                inode.children[paths[i]] = child
            inode = child
        leaf = _HNode(str(job.uid), 1.0, attr,
                      request=job.total_request.clone(), children=None)
        inode.children[str(job.uid)] = leaf

    def _update_hierarchical_share(self, node: _HNode,
                                   demanding: Dict[str, bool]) -> None:
        if node.children is None:
            node.saturated = _resource_saturated(
                node.attr.allocated, node.request, demanding)
            return
        mdr = 1.0
        for child in node.children.values():
            self._update_hierarchical_share(child, demanding)
            if child.attr.share != 0 and not child.saturated:
                _, res_share = self.calculate_share(
                    child.attr.allocated, self.total_resource)
                if res_share < mdr:
                    mdr = res_share
        node.attr.allocated = Resource()
        saturated = True
        for child in node.children.values():
            if not child.saturated:
                saturated = False
            if child.attr.share != 0:
                if child.saturated:
                    node.attr.allocated.add(child.attr.allocated)
                else:
                    node.attr.allocated.add(
                        child.attr.allocated.clone().scale(
                            mdr / child.attr.share))
        node.attr.dominant_resource, node.attr.share = self.calculate_share(
            node.attr.allocated, self.total_resource)
        node.saturated = saturated

    def update_hierarchical_share(self, root, total_allocated, job, attr,
                                  hierarchy, weights) -> None:
        demanding = {}
        for rn in self.total_resource.resource_names():
            if total_allocated.get(rn) < self.total_resource.get(rn):
                demanding[rn] = True
        self._build_hierarchy(root, job, attr, hierarchy, weights)
        self._update_hierarchical_share(root, demanding)

    def _compare_queues(self, root: _HNode, lqueue, rqueue) -> float:
        lnode, rnode = root, root
        lpaths = lqueue.hierarchy.split("/")
        rpaths = rqueue.hierarchy.split("/")
        depth = min(len(lpaths), len(rpaths))
        for i in range(depth):
            if not lnode.saturated and rnode.saturated:
                return -1
            if lnode.saturated and not rnode.saturated:
                return 1
            lkey = lnode.attr.share / lnode.weight
            rkey = rnode.attr.share / rnode.weight
            if lkey == rkey:
                if i < depth - 1:
                    lnode = (lnode.children or {}).get(lpaths[i + 1])
                    rnode = (rnode.children or {}).get(rpaths[i + 1])
                    if lnode is None or rnode is None:
                        return 0
            else:
                return lkey - rkey
        return 0

    # -- session wiring -----------------------------------------------------

    def on_session_open(self, ssn) -> None:
        from ..api import allocated_status

        self.total_resource = ssn.total_allocatable().clone()

        # feed the solver: per-round dominant-share job ordering runs as
        # on-device reductions (SURVEY §7 stage 4); allocate fills the
        # flatten's job_drf_allocated/drf_total arrays from these attrs.
        # Honors the tier's enabledJobOrder gate like the host dispatch
        # (session.py _tier_fns), so a config that disabled DRF ordering
        # doesn't get it back on the solver path.
        from ..framework.session import _enabled
        if any(opt.name == self.name()
               and _enabled(opt, "enabled_job_order")
               for tier in ssn.tiers for opt in tier.plugins):
            ssn.solver_options["drf_order"] = {
                "job_attrs": self.job_attrs,
                "total": self.total_resource,
                # hdrf: the allocate action builds the queue-path tree
                # arrays (ops.hdrf) and the kernel re-ranks by the
                # hierarchical comparator instead of plain shares
                "hierarchy": self._hierarchy_enabled(ssn),
                "total_allocated": self.total_allocated,
            }

        namespace_order = self._namespace_order_enabled(ssn)
        hierarchy = self._hierarchy_enabled(ssn)

        for job in ssn.jobs.values():
            # JobInfo.allocated is the maintained sum over allocated-status
            # tasks — the same set drf.go:201-214 iterates — so the session
            # open is O(jobs), not O(tasks)
            attr = _DrfAttr(job.allocated.clone())
            self.job_attrs[job.uid] = attr
            # plain mode orders only jobs with Pending tasks, and the
            # victim fns recompute shares from attr.allocated on the fly,
            # so the per-job share precompute (+ gauge write) is skipped
            # for the steady-state bulk of running jobs; namespace and
            # hierarchy modes aggregate over every job and keep it
            if namespace_order or hierarchy \
                    or TaskStatus.PENDING in job.task_status_index:
                self._update_job_share(job.namespace, job.name, attr)

            if namespace_order:
                ns_opt = self.namespace_opts.setdefault(
                    job.namespace, _DrfAttr())
                ns_opt.allocated.add(attr.allocated)
                self._update_namespace_share(job.namespace, ns_opt)
            if hierarchy:
                queue = ssn.queues.get(job.queue)
                if queue is not None:
                    self.total_allocated.add(attr.allocated)
                    self.update_hierarchical_share(
                        self.hierarchical_root, self.total_allocated, job,
                        attr, queue.hierarchy, queue.weights)

        def preemptable_fn(preemptor, preemptees):
            victims = []
            pool = preemptees
            if namespace_order:
                l_ns_info = ssn.namespace_info.get(preemptor.namespace)
                l_weight = l_ns_info.get_weight() if l_ns_info else 1
                l_att = self.namespace_opts.get(preemptor.namespace, _DrfAttr())
                l_alloc = l_att.allocated.clone().add(preemptor.resreq)
                _, l_share = self.calculate_share(l_alloc, self.total_resource)
                l_weighted = l_share / l_weight

                ns_allocation: Dict[str, Resource] = {}
                undecided = []
                for preemptee in pool:
                    if preemptor.namespace == preemptee.namespace:
                        undecided.append(preemptee)
                        continue
                    if preemptee.namespace not in ns_allocation:
                        r_att = self.namespace_opts.get(
                            preemptee.namespace, _DrfAttr())
                        ns_allocation[preemptee.namespace] = \
                            r_att.allocated.clone()
                    r_ns_info = ssn.namespace_info.get(preemptee.namespace)
                    r_weight = r_ns_info.get_weight() if r_ns_info else 1
                    r_alloc = ns_allocation[preemptee.namespace]
                    try:
                        r_alloc.sub(preemptee.resreq)
                    except ValueError:
                        r_alloc = Resource()
                    _, r_share = self.calculate_share(
                        r_alloc, self.total_resource)
                    r_weighted = r_share / r_weight
                    if l_weighted < r_weighted:
                        victims.append(preemptee)
                        continue
                    if l_weighted - r_weighted > SHARE_DELTA:
                        continue
                    undecided.append(preemptee)
                pool = undecided

            l_att = self.job_attrs.get(preemptor.job, _DrfAttr())
            l_alloc = l_att.allocated.clone().add(preemptor.resreq)
            _, ls = self.calculate_share(l_alloc, self.total_resource)
            allocations: Dict[str, Resource] = {}
            for preemptee in pool:
                if preemptee.job not in allocations:
                    r_att = self.job_attrs.get(preemptee.job, _DrfAttr())
                    allocations[preemptee.job] = r_att.allocated.clone()
                r_alloc = allocations[preemptee.job]
                try:
                    r_alloc.sub(preemptee.resreq)
                except ValueError:
                    pass
                _, rs = self.calculate_share(r_alloc, self.total_resource)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name(), preemptable_fn)

        if hierarchy:
            def queue_order_fn(l, r):
                ret = self._compare_queues(self.hierarchical_root, l, r)
                return -1 if ret < 0 else (1 if ret > 0 else 0)

            ssn.add_queue_order_fn(self.name(), queue_order_fn)

            def reclaimable_fn(reclaimer, reclaimees):
                victims = []
                total_allocated = self.total_allocated.clone()
                root = self.hierarchical_root.clone()
                ljob = ssn.jobs.get(reclaimer.job)
                lqueue = ssn.queues.get(ljob.queue)
                lattr = _DrfAttr(self.job_attrs[ljob.uid].allocated.clone())
                lattr.allocated.add(reclaimer.resreq)
                total_allocated.add(reclaimer.resreq)
                self._update_share(lattr)
                self.update_hierarchical_share(
                    root, total_allocated, ljob.clone(), lattr,
                    lqueue.hierarchy, lqueue.weights)
                for preemptee in reclaimees:
                    rjob = ssn.jobs.get(preemptee.job)
                    rqueue = ssn.queues.get(rjob.queue)
                    try:
                        total_allocated.sub(preemptee.resreq)
                    except ValueError:
                        pass
                    rattr = _DrfAttr(
                        self.job_attrs[rjob.uid].allocated.clone())
                    try:
                        rattr.allocated.sub(preemptee.resreq)
                    except ValueError:
                        pass
                    self._update_share(rattr)
                    self.update_hierarchical_share(
                        root, total_allocated, rjob.clone(), rattr,
                        rqueue.hierarchy, rqueue.weights)
                    ret = self._compare_queues(root, lqueue, rqueue)
                    # restore
                    total_allocated.add(preemptee.resreq)
                    rattr.allocated.add(preemptee.resreq)
                    self._update_share(rattr)
                    self.update_hierarchical_share(
                        root, total_allocated, rjob.clone(), rattr,
                        rqueue.hierarchy, rqueue.weights)
                    if ret < 0:
                        victims.append(preemptee)
                return victims

            ssn.add_reclaimable_fn(self.name(), reclaimable_fn)

        def job_order_fn(l, r):
            ls = self.job_attrs[l.uid].share
            rs = self.job_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_job_order_fn(self.name(), job_order_fn)
        ssn.add_order_key_fn("job_order_fns", self.name(),
                             lambda j: self.job_attrs[j.uid].share)
        # the share key is live-share-dependent: share = f(job.allocated,
        # cluster total). job.allocated churn is version-gated (the
        # OrderCache re-keys dirty jobs), but the TOTAL is cluster-wide
        # state — declare it as the key's context so a node add/remove/
        # respec invalidates every cached share-ordered position instead
        # of silently re-ranking only the churned jobs
        total = self.total_resource
        ssn.add_order_key_context_fn(
            "job_order_fns", self.name(),
            lambda: (total.milli_cpu, total.memory,
                     tuple(sorted(total.scalars.items()))))

        if namespace_order:
            def namespace_order_fn(l, r):
                l_opt = self.namespace_opts.get(l, _DrfAttr())
                r_opt = self.namespace_opts.get(r, _DrfAttr())
                l_info = ssn.namespace_info.get(l)
                r_info = ssn.namespace_info.get(r)
                lw = l_info.get_weight() if l_info else 1
                rw = r_info.get_weight() if r_info else 1
                lws, rws = l_opt.share / lw, r_opt.share / rw
                metrics.namespace_weight.set(lw, {"namespace_name": str(l)})
                metrics.namespace_weight.set(rw, {"namespace_name": str(r)})
                if lws == rws:
                    return 0
                return -1 if lws < rws else 1

            ssn.add_namespace_order_fn(self.name(), namespace_order_fn)

        def on_allocate(event):
            attr = self.job_attrs.get(event.task.job)
            if attr is None:
                return
            attr.allocated.add(event.task.resreq)
            job = ssn.jobs.get(event.task.job)
            self._update_job_share(job.namespace, job.name, attr)
            if namespace_order:
                ns_opt = self.namespace_opts.setdefault(
                    event.task.namespace, _DrfAttr())
                ns_opt.allocated.add(event.task.resreq)
                self._update_namespace_share(event.task.namespace, ns_opt)
            if hierarchy:
                queue = ssn.queues.get(job.queue)
                if queue is not None:
                    self.total_allocated.add(event.task.resreq)
                    self.update_hierarchical_share(
                        self.hierarchical_root, self.total_allocated, job,
                        attr, queue.hierarchy, queue.weights)

        def on_deallocate(event):
            attr = self.job_attrs.get(event.task.job)
            if attr is None:
                return
            try:
                attr.allocated.sub(event.task.resreq)
            except ValueError:
                pass
            job = ssn.jobs.get(event.task.job)
            self._update_job_share(job.namespace, job.name, attr)
            if namespace_order:
                ns_opt = self.namespace_opts.setdefault(
                    event.task.namespace, _DrfAttr())
                try:
                    ns_opt.allocated.sub(event.task.resreq)
                except ValueError:
                    pass
                self._update_namespace_share(event.task.namespace, ns_opt)
            if hierarchy:
                queue = ssn.queues.get(job.queue)
                if queue is not None:
                    try:
                        self.total_allocated.sub(event.task.resreq)
                    except ValueError:
                        pass
                    self.update_hierarchical_share(
                        self.hierarchical_root, self.total_allocated, job,
                        attr, queue.hierarchy, queue.weights)

        def on_allocate_batch(tasks):
            """Additive form of on_allocate: one aggregate add + one share
            recompute per job (shares depend only on totals)."""
            by_job: Dict[str, list] = {}
            for t in tasks:
                group = by_job.get(t.job)
                if group is None:
                    by_job[t.job] = [t]
                else:
                    group.append(t)
            for juid, group in by_job.items():
                agg = Resource.sum_of(t.resreq for t in group)
                attr = self.job_attrs.get(juid)
                if attr is None:
                    continue
                attr.allocated.add(agg)
                job = ssn.jobs.get(juid)
                self._update_job_share(job.namespace, job.name, attr)
                if namespace_order:
                    ns_opt = self.namespace_opts.setdefault(
                        job.namespace, _DrfAttr())
                    ns_opt.allocated.add(agg)
                    self._update_namespace_share(job.namespace, ns_opt)
                if hierarchy:
                    queue = ssn.queues.get(job.queue)
                    if queue is not None:
                        self.total_allocated.add(agg)
                        self.update_hierarchical_share(
                            self.hierarchical_root, self.total_allocated,
                            job, attr, queue.hierarchy, queue.weights)

        ssn.add_event_handler(EventHandler(
            allocate_func=on_allocate, deallocate_func=on_deallocate,
            batch_allocate_func=on_allocate_batch))

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource()
        self.total_allocated = Resource()
        self.job_attrs = {}
        self.namespace_opts = {}
        self.hierarchical_root = _HNode("root", 1.0, children={})
