"""Conformance plugin (reference plugins/conformance/conformance.go:44-66).

Never evict system-critical pods or anything in kube-system.
"""

from __future__ import annotations

from ..framework import Plugin

_CRITICAL_PRIORITY_CLASSES = ("system-cluster-critical", "system-node-critical")


def _evictable(task) -> bool:
    pod = task.pod
    if pod.namespace == "kube-system":
        return False
    if pod.priority_class_name in _CRITICAL_PRIORITY_CLASSES:
        return False
    return True


class ConformancePlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "conformance"

    def on_session_open(self, ssn) -> None:
        def evictable_fn(evictor, evictees):
            return [t for t in evictees if _evictable(t)]

        ssn.add_preemptable_fn(self.name(), evictable_fn)
        ssn.add_reclaimable_fn(self.name(), evictable_fn)

    def on_session_close(self, ssn) -> None:
        pass
