"""Proportion plugin (reference plugins/proportion/proportion.go:75-326).

Weighted fair-share of the cluster among queues: iterative water-filling of
per-queue `deserved` by weight, clamped by capability and request; overused,
reclaimable and job-enqueueable checks derive from it.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api import Resource, allocated_status, TaskStatus
from ..framework import EventHandler, Plugin
from ..metrics import metrics
from ..models import PodGroupPhase
from .drf import share


class _QueueAttr:
    __slots__ = ("queue_id", "name", "weight", "deserved", "allocated",
                 "request", "inqueue", "capability", "share")

    def __init__(self, queue_id: str, name: str, weight: int):
        self.queue_id = queue_id
        self.name = name
        self.weight = max(int(weight or 1), 1)
        self.deserved = Resource()
        self.allocated = Resource()
        self.request = Resource()
        self.inqueue = Resource()
        self.capability: Optional[Resource] = None
        self.share = 0.0


def _min_resource(l: Resource, r: Resource) -> Resource:
    out = Resource(min(l.milli_cpu, r.milli_cpu), min(l.memory, r.memory))
    for k, v in l.scalars.items():
        out.scalars[k] = min(v, r.scalars.get(k, 0.0))
    return out


class ProportionPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.total_resource = Resource()
        self.queue_opts: Dict[str, _QueueAttr] = {}

    def name(self) -> str:
        return "proportion"

    def _update_share(self, attr: _QueueAttr) -> None:
        res = 0.0
        for rn in attr.deserved.resource_names():
            s = share(attr.allocated.get(rn), attr.deserved.get(rn))
            res = max(res, s)
        attr.share = res
        metrics.queue_share.set(res, {"queue_name": attr.name})

    def on_session_open(self, ssn) -> None:
        self.total_resource = ssn.total_allocatable().clone()

        for job in ssn.jobs.values():
            if job.queue not in self.queue_opts:
                queue = ssn.queues.get(job.queue)
                if queue is None:
                    continue
                attr = _QueueAttr(queue.uid, queue.name, queue.weight)
                if queue.capability:
                    attr.capability = Resource.from_resource_list(
                        queue.capability)
                self.queue_opts[job.queue] = attr
            attr = self.queue_opts[job.queue]
            # maintained aggregates (job_info) replace the per-task loops of
            # proportion.go:120-134: allocated = allocated-status sum,
            # request = allocated + pending sums — O(jobs) per session open
            attr.allocated.add(job.allocated)
            attr.request.add(job.allocated)
            attr.request.add(job.pending_request)
            if job.pod_group.status.phase == PodGroupPhase.INQUEUE:
                attr.inqueue.add(Resource.from_resource_list(
                    job.pod_group.spec.min_resources or {}))

        for attr in self.queue_opts.values():
            metrics.update_queue_metrics(attr.name, attr.allocated,
                                         attr.request)
            metrics.queue_weight.set(attr.weight, {"queue_name": attr.name})

        # iterative water-filling (proportion.go:137-197)
        remaining = self.total_resource.clone()
        meet = set()
        while True:
            total_weight = sum(a.weight for a in self.queue_opts.values()
                               if a.queue_id not in meet)
            if total_weight == 0:
                break
            increased, decreased = Resource(), Resource()
            for attr in self.queue_opts.values():
                if attr.queue_id in meet:
                    continue
                old_deserved = attr.deserved.clone()
                attr.deserved.add(
                    remaining.clone().multi(attr.weight / total_weight))
                if attr.capability is not None and \
                        not attr.deserved.less_equal_strict(attr.capability):
                    attr.deserved = _min_resource(attr.deserved,
                                                  attr.capability)
                    attr.deserved = _min_resource(attr.deserved, attr.request)
                    meet.add(attr.queue_id)
                elif attr.request.less(attr.deserved):
                    attr.deserved = _min_resource(attr.deserved, attr.request)
                    meet.add(attr.queue_id)
                self._update_share(attr)
                inc, dec = attr.deserved.diff(old_deserved)
                increased.add(inc)
                decreased.add(dec)
                metrics.queue_deserved_milli_cpu.set(
                    attr.deserved.milli_cpu, {"queue_name": attr.name})
                metrics.queue_deserved_memory_bytes.set(
                    attr.deserved.memory, {"queue_name": attr.name})
            try:
                remaining.sub(increased)
            except ValueError:
                remaining = Resource()
            remaining.add(decreased)
            if remaining.is_empty():
                break

        def queue_order_fn(l, r):
            la = self.queue_opts.get(l.uid)
            ra = self.queue_opts.get(r.uid)
            if la is None or ra is None:
                return 0
            if la.share == ra.share:
                return 0
            return -1 if la.share < ra.share else 1

        ssn.add_queue_order_fn(self.name(), queue_order_fn)

        # publish per-queue attrs so the allocate solver can water-fill
        # deserved on device and cap per-round admissions per queue
        ssn.solver_options["queue_opts"] = self.queue_opts
        # proportion.workConserving=false pins the solver to strict
        # reference parity: no overflow phases, no unrequested-dim cap
        # easing (ADVICE r2 #1 — operators who need proportion.go:245's
        # any-dim overused behavior byte-for-byte can opt out of the
        # strandings-avoidance improvements)
        from ..framework import Arguments
        args = (self.arguments if isinstance(self.arguments, Arguments)
                else Arguments(self.arguments))
        ssn.solver_options["work_conserving"] = args.get_bool(
            "proportion.workConserving", True)

        def reclaimable_fn(reclaimer, reclaimees):
            victims = []
            allocations: Dict[str, Resource] = {}
            for reclaimee in reclaimees:
                job = ssn.jobs.get(reclaimee.job)
                attr = self.queue_opts.get(job.queue)
                if attr is None:
                    continue
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                allocated = allocations[job.queue]
                if allocated.less(reclaimee.resreq):
                    continue
                try:
                    allocated.sub(reclaimee.resreq)
                except ValueError:
                    continue
                if attr.deserved.less_equal_strict(allocated):
                    victims.append(reclaimee)
            return victims

        ssn.add_reclaimable_fn(self.name(), reclaimable_fn)

        def overused_fn(queue) -> bool:
            attr = self.queue_opts.get(queue.uid)
            if attr is None:
                return False
            overused = not attr.allocated.less_equal(attr.deserved)
            metrics.queue_overused.set(
                1.0 if overused else 0.0, {"queue_name": attr.name})
            return overused

        ssn.add_overused_fn(self.name(), overused_fn)

        def job_enqueueable_fn(job) -> bool:
            attr = self.queue_opts.get(job.queue)
            queue = ssn.queues.get(job.queue)
            if attr is None or queue is None:
                return True
            if not queue.capability:
                return True
            if not job.pod_group.spec.min_resources:
                return True
            min_req = Resource.from_resource_list(
                job.pod_group.spec.min_resources)
            cap = Resource.from_resource_list(queue.capability)
            total = min_req.clone().add(attr.allocated).add(attr.inqueue)
            if total.less_equal(cap):
                attr.inqueue.add(min_req)
                return True
            return False

        ssn.add_job_enqueueable_fn(self.name(), job_enqueueable_fn)

        def on_allocate(event):
            job = ssn.jobs.get(event.task.job)
            attr = self.queue_opts.get(job.queue)
            if attr is None:
                return
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)

        def on_deallocate(event):
            job = ssn.jobs.get(event.task.job)
            attr = self.queue_opts.get(job.queue)
            if attr is None:
                return
            try:
                attr.allocated.sub(event.task.resreq)
            except ValueError:
                pass
            self._update_share(attr)

        def on_allocate_batch(tasks):
            """Additive form: one aggregate add + one share recompute per
            queue (share depends only on the allocated total)."""
            by_queue: Dict[str, list] = {}
            last_job = None  # statements fire per job: one lookup suffices
            queue = None
            for t in tasks:
                if t.job != last_job:
                    job = ssn.jobs.get(t.job)
                    queue = job.queue if job is not None else None
                    last_job = t.job
                if queue is None:
                    continue
                group = by_queue.get(queue)
                if group is None:
                    by_queue[queue] = [t]
                else:
                    group.append(t)
            for qname, group in by_queue.items():
                attr = self.queue_opts.get(qname)
                if attr is None:
                    continue
                attr.allocated.add(Resource.sum_of(t.resreq for t in group))
                self._update_share(attr)

        ssn.add_event_handler(EventHandler(
            allocate_func=on_allocate, deallocate_func=on_deallocate,
            batch_allocate_func=on_allocate_batch))

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource()
        self.queue_opts = {}
