"""Reservation plugin (reference plugins/reservation/reservation.go:44-141).

Target job = highest priority then longest-waiting Pending job; reserved
node = unlocked node with maximum idle.
"""

from __future__ import annotations

import time

from ..framework import Plugin
from ..utils.scheduler_helper import reservation


class ReservationPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "reservation"

    def on_session_open(self, ssn) -> None:
        def target_job_fn(jobs):
            if not jobs:
                return None
            highest = max(j.priority for j in jobs)
            candidates = [j for j in jobs if j.priority == highest]
            # longest waiting = earliest schedule start
            def waited(job):
                start = getattr(job, "schedule_start_timestamp", None) \
                    or job.creation_timestamp or time.time()
                return time.time() - start
            return max(candidates, key=waited)

        ssn.add_target_job_fn(self.name(), target_job_fn)

        def reserved_nodes_fn():
            best = None
            for node in ssn.nodes.values():
                if node.name in reservation.locked_nodes:
                    continue
                if best is None or best.idle.less_equal(node.idle):
                    best = node
            if best is not None:
                reservation.locked_nodes[best.name] = best

        ssn.add_reserved_nodes_fn(self.name(), reserved_nodes_fn)

    def on_session_close(self, ssn) -> None:
        pass
