"""Priority plugin (reference plugins/priority/priority.go:43-107)."""

from __future__ import annotations

from ..framework import Plugin


class PriorityPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "priority"

    def on_session_open(self, ssn) -> None:
        def task_order_fn(l, r):
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_task_order_fn(self.name(), task_order_fn)
        ssn.add_order_key_fn("task_order_fns", self.name(),
                             lambda t: -t.priority)

        def job_order_fn(l, r):
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_job_order_fn(self.name(), job_order_fn)
        ssn.add_order_key_fn("job_order_fns", self.name(),
                             lambda j: -j.priority)
        # JobInfo.priority is resolved from the priority-class table at
        # every snapshot WITHOUT bumping the job's version, so the key is
        # not a pure function of the job clone: declare the table as the
        # key's context so cached orders go stale when a class is edited.
        # (Task priority needs no context — pods carry their admission-
        # resolved value.)
        cache = getattr(ssn, "cache", None)

        def _pclass_context():
            pcs = getattr(cache, "priority_classes", None) or {}
            return (getattr(cache, "default_priority", 0),
                    tuple(sorted((n, getattr(pc, "value", 0))
                                 for n, pc in pcs.items())))

        ssn.add_order_key_context_fn("job_order_fns", self.name(),
                                     _pclass_context)

        def preemptable_fn(preemptor, preemptees):
            """Victims must belong to strictly lower-priority jobs."""
            p_job = ssn.jobs.get(preemptor.job)
            if p_job is None:
                return []
            victims = []
            for preemptee in preemptees:
                job = ssn.jobs.get(preemptee.job)
                if job is not None and job.priority < p_job.priority:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name(), preemptable_fn)

    def on_session_close(self, ssn) -> None:
        pass
