"""Node-axis-sharded eviction solve (preempt/reclaim at mesh scale).

Victims partition naturally by the node that hosts them, so the victim
axis shards EXACTLY like the node axis of the allocate solver
(parallel/sharded_solver.py): the host re-lays victims out per node
shard (shard_victims), each device runs the per-job closed-form
eviction-minimal solve (ops/evict.py solve_evict_uniform) over its own
nodes + victims, and the only cross-device traffic per job step is one
psum of the absorbable-count total plus [N]-vector all_gathers for the
score-ordered spread — the same ICI profile as the allocate kernel.

The per-task scan kernel (solve_evict) stays single-device: its victim
prefix walk is sequential per claimer and does not dominate at scale;
the uniform gang path here is the scale path (BENCH config #4).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.evict import EvictResult, absorb_counts, spread_counts
from ..ops.solver import NEG, _segment_prefix, le_fits, score_matrix
from .sharded_solver import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def shard_victims(victims: Dict[str, np.ndarray], N: int, D: int):
    """Re-lay victim arrays so each device's slice holds exactly the
    victims of its node shard (cheapest-first order per node preserved).
    Returns (sharded victims dict, perm) where perm[i] = original victim
    index at sharded slot i (-1 for padding)."""
    v_node = np.asarray(victims["v_node"])
    v_valid = np.asarray(victims["v_valid"])
    n_loc = N // D
    shard_of = np.where(v_valid, v_node // n_loc, -1)
    per_shard = [np.nonzero(shard_of == d)[0] for d in range(D)]
    v_cap = max((len(p) for p in per_shard), default=1)
    v_cap = max(v_cap, 1)
    V2 = v_cap * D
    R = victims["v_req"].shape[1]
    J = victims["elig"].shape[0]
    out = {
        "v_req": np.zeros((V2, R), np.float32),
        "v_node": np.zeros(V2, np.int32),
        "v_valid": np.zeros(V2, bool),
        "elig": np.zeros((J, V2), bool),
        "job_need": np.asarray(victims["job_need"]),
        "job_req": np.asarray(victims["job_req"]),
        "job_acct": np.asarray(victims["job_acct"]),
        "job_count": np.asarray(victims["job_count"]),
    }
    perm = np.full(V2, -1, np.int32)
    for d, idxs in enumerate(per_shard):
        sl = slice(d * v_cap, d * v_cap + len(idxs))
        out["v_req"][sl] = victims["v_req"][idxs]
        out["v_node"][sl] = v_node[idxs]
        out["v_valid"][sl] = True
        out["elig"][:, sl] = np.asarray(victims["elig"])[:, idxs]
        perm[d * v_cap:d * v_cap + len(idxs)] = idxs
    return out, perm


@functools.partial(jax.jit, static_argnames=(
    "mesh", "score_families", "require_freed_covers", "stop_at_need"))
def _solve_sharded(arrays, victims, score_params, mesh,
                   score_families, require_freed_covers, stop_at_need):
    a = arrays
    v = victims
    T = a["task_init_req"].shape[0]
    N = a["node_idle"].shape[0]
    J = a["job_min"].shape[0]
    D = mesh.devices.size
    thr = a["thresholds"]
    sm = a["scalar_dim_mask"]

    in_specs_a = {
        "task_init_req": P(), "task_req": P(), "task_job": P(),
        "task_rank": P(), "task_sig": P(), "task_valid": P(),
        "job_min": P(), "job_valid": P(),
        "node_idle": P("n", None), "node_extra_future": P("n", None),
        "node_used": P("n", None), "node_alloc": P("n", None),
        "node_valid": P("n"),
        "sig_masks": P(None, "n"), "thresholds": P(),
        "scalar_dim_mask": P(),
    }
    in_specs_v = {
        "v_req": P("n", None), "v_node": P("n"), "v_valid": P("n"),
        "elig": P(None, "n"), "job_need": P(), "job_req": P(),
        "job_acct": P(), "job_count": P(),
    }
    params_spec = {k: (P("n") if k == "node_static" else P())
                   for k in score_params}

    # static D=1 fast path: every all_gather degrades to identity and is
    # skipped at trace time (same contract as parallel/sharded_solver.py)
    D1 = D == 1

    def kernel(a, v, sp):
        n_loc = a["node_idle"].shape[0]
        my_base = jnp.int32(0) if D1 \
            else jax.lax.axis_index("n") * n_loc

        def gather(x):
            return x if D1 else jax.lax.all_gather(x, "n", tiled=True)
        v_req = v["v_req"]
        v_node_loc = v["v_node"] - my_base          # local node index
        v_valid = v["v_valid"]
        elig = v["elig"]
        need = v["job_need"]
        job_req = v["job_req"]
        job_acct = v["job_acct"]
        job_count = v["job_count"]
        V = v_req.shape[0]
        future0 = a["node_idle"] + a["node_extra_future"]
        job_score_loc = score_matrix(job_req, future0, a["node_used"],
                                     a["node_alloc"], sp, score_families)
        seg_start = jnp.concatenate(
            [jnp.array([True]), v_node_loc[1:] != v_node_loc[:-1]])
        vidx = jnp.arange(V)
        sig_feas_t = a["sig_masks"][a["task_sig"]] | ~a["task_valid"][:, None]
        job_feas_loc = jnp.ones((J, n_loc), jnp.int32).at[a["task_job"]].min(
            sig_feas_t.astype(jnp.int32)) > 0
        first_task = jnp.full((J,), T - 1, jnp.int32).at[
            a["task_job"]].min(jnp.arange(T, dtype=jnp.int32))
        task_pos = jnp.arange(T, dtype=jnp.int32) - first_task[a["task_job"]]

        def step(carry, j):
            future, alive, evby, assigned, jalloc = carry
            r = job_req[j]
            sig = jnp.where(sm, r > 10.0, r > 0.0)
            r_fit = jnp.where(sig, r, 0.0)
            count = (jnp.minimum(job_count[j], need[j]) if stop_at_need
                     else job_count[j])
            active = a["job_valid"][j] & (count > 0)

            elig_v = elig[j] & alive & v_valid
            vreq_m = v_req * elig_v[:, None]
            prefix_incl = _segment_prefix(vreq_m, seg_start) + vreq_m
            ptot = jax.ops.segment_sum(
                vreq_m, jnp.clip(v_node_loc, 0, n_loc - 1),
                num_segments=n_loc)
            has_v = jax.ops.segment_max(
                elig_v.astype(jnp.int32), jnp.clip(v_node_loc, 0, n_loc - 1),
                num_segments=n_loc) > 0
            base = (jnp.zeros_like(future) if require_freed_covers
                    else future)
            # per-node absorption counts: SAME math as the single-device
            # kernel (ops/evict.py absorb_counts), on this shard's nodes
            feas_n = job_feas_loc[j] & a["node_valid"]
            m_all_loc, f_loc, cap_loc = absorb_counts(
                r, r_fit, sig, base, ptot, has_v, feas_n, thr, sm,
                float(T))

            # replicated spread over gathered [N] vectors (same math as
            # ops/evict.py spread_counts)
            score_all = gather(job_score_loc[j])
            m_all = gather(m_all_loc)
            f_all = gather(f_loc)
            cap_extra = gather(cap_loc)

            total = jnp.sum(m_all).astype(jnp.int32)
            satisfied = (total >= need[j]) if stop_at_need \
                else jnp.bool_(True)
            do = active & satisfied & (total > 0)
            count = jnp.where(do, jnp.minimum(count, total), 0)

            score_j = jnp.where(m_all > 0, score_all, NEG)
            c, order, cum = spread_counts(count, score_j, m_all, f_all,
                                          cap_extra)

            is_mine = (a["task_job"] == j) & a["task_valid"]
            p = task_pos
            node_for_p = order[jnp.clip(
                jnp.searchsorted(cum, p.astype(cum.dtype), side="right"),
                0, N - 1)]
            placed_t = is_mine & (p < count)
            assigned = jnp.where(placed_t, node_for_p.astype(jnp.int32),
                                 assigned)

            # local eviction for this shard's slice of c
            c_loc = jax.lax.dynamic_slice(c, (my_base,), (n_loc,))
            demand_fit = c_loc.astype(jnp.float32)[:, None] \
                * r_fit[None, :]
            demand_acct = c_loc.astype(jnp.float32)[:, None] \
                * job_acct[j][None, :]
            fit_now_n = le_fits(demand_fit, base, thr, sm,
                                ignore_req=demand_fit)
            need_evict_n = (c_loc > 0) & ~fit_now_n
            vloc = jnp.clip(v_node_loc, 0, n_loc - 1)
            fit_at = le_fits(demand_fit[vloc], base[vloc] + prefix_incl,
                             thr, sm, ignore_req=demand_fit[vloc]) & elig_v
            cut = jax.ops.segment_min(jnp.where(fit_at, vidx, V), vloc,
                                      num_segments=n_loc)
            ev = (elig_v & need_evict_n[vloc] & (vidx <= cut[vloc])
                  & (cut[vloc] < V))
            freed = jax.ops.segment_sum(v_req * ev[:, None], vloc,
                                        num_segments=n_loc)
            future = future + freed - demand_acct
            alive = alive & ~ev
            evby = jnp.where(ev, j, evby)
            jalloc = jalloc.at[j].add(count)
            return (future, alive, evby, assigned, jalloc), None

        init = (future0, v_valid, jnp.full((V,), -1, jnp.int32),
                jnp.full((T,), -1, jnp.int32), jnp.zeros(J, jnp.int32))
        carry, _ = jax.lax.scan(step, init, jnp.arange(J))
        future, alive, evby, assigned, jalloc = carry
        # gather local victim verdicts into the sharded global layout
        evby_all = gather(evby)
        return assigned, evby_all, jalloc

    mapped = shard_map(
        kernel, mesh=mesh,
        in_specs=(in_specs_a, in_specs_v, params_spec),
        out_specs=(P(), P(), P()))
    assigned, evby, jalloc = mapped(
        {k: a[k] for k in in_specs_a}, {k: v[k] for k in in_specs_v},
        dict(score_params))
    return assigned, evby, jalloc


def solve_evict_uniform_sharded(arrays, victims, score_params, mesh: Mesh,
                                score_families: Tuple[str, ...] = ("kube",),
                                require_freed_covers: bool = False,
                                stop_at_need: bool = True) -> EvictResult:
    """Host wrapper: shard the victims by node shard, run the mesh kernel,
    scatter the verdicts back to the caller's victim order."""
    N = arrays["node_idle"].shape[0]
    D = mesh.devices.size
    assert N % D == 0, \
        f"device count {D} must divide the node axis {N}"
    sharded, perm = shard_victims(victims, N, D)
    assigned, evby_s, jalloc = _solve_sharded(
        arrays, sharded, score_params, mesh, score_families,
        require_freed_covers, stop_at_need)
    evby_s = np.asarray(evby_s)
    V = victims["v_req"].shape[0]
    evby = np.full(V, -1, np.int32)
    live = perm >= 0
    evby[perm[live]] = evby_s[live]
    return EvictResult(assigned=np.asarray(assigned), evicted_by=evby,
                       job_placed=np.asarray(jalloc))
