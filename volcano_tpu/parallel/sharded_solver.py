"""Node-axis-sharded allocate solver: shard_map over a device mesh.

Scaling axis (SURVEY.md §5.7-5.8): the reference bounds per-task work on
big clusters by sampling nodes; the TPU build shards the node axis of the
task x node problem across the mesh instead. Layout:

- node arrays ([N,R] idle/used/alloc, [N] npods/valid, sig_masks[S,N]) are
  sharded along the mesh 'n' axis;
- task/job arrays ([T,*], [J]) are replicated;
- each device computes feasibility/scores for its node shard only (the
  [T, N/D] matrices are the memory hog), admission prefix-sums run
  node-locally, and the small cross-device exchanges are [N] score/slot
  vectors (all_gather) and [T] choice/admit vectors (psum/pmax) over ICI.

The gang fixpoint and round loop conditions depend only on replicated
values, so every device executes identical trip counts.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map as _shard_map
    _REP_KWARG = "check_vma"
except ImportError:  # older jax: experimental API, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KWARG = "check_rep"
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.solver import (
    NEG, BIG_KEY, SolveResult, _queue_cap_mask, _segment_prefix,
    drf_state, fits_matrix, le_fits, queue_cap_state, score_matrix,
)


def shard_map(*args, **kwargs):
    """shard_map with replication checking off, spelled for either jax API."""
    kwargs[_REP_KWARG] = False
    return _shard_map(*args, **kwargs)


def make_mesh(devices=None, axis: str = "n") -> Mesh:
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), (axis,))


def arena_mesh(devices=None, axis: str = "n", max_devices: int = 0) -> Mesh:
    """Mesh over the largest power-of-two device prefix: the padded node
    axis is always a multiple of 8 (ops.arrays.bucket quarter-steps,
    floor 8), so any power-of-two D <= 8 divides it evenly — a 6-device
    host would otherwise fail the sharded solver's N % D check."""
    if devices is None:
        devices = jax.devices()
    if max_devices:
        devices = devices[:max_devices]
    d = 1
    while d * 2 <= len(devices) and d < 8:
        d *= 2
    return make_mesh(list(devices)[:d], axis)


#: static solve flags solve_allocate_sharded_packed2d accepts — a strict
#: subset of the single-device entries' (no work_conserving/per_node_cap);
#: the bucket prewarmer filters a session's flag set against this before
#: warming the sharded variant (ops.precompile.BucketPrewarmer)
PACKED2D_FLAGS = ("max_rounds", "max_gang_iters", "herd_mode",
                  "score_families", "use_queue_cap", "use_drf_order",
                  "use_hdrf_order", "fused")


@functools.partial(jax.jit, static_argnames=("mesh", "max_rounds",
                                             "max_gang_iters", "herd_mode",
                                             "score_families",
                                             "use_queue_cap",
                                             "use_drf_order",
                                             "use_hdrf_order", "fused"))
def solve_allocate_sharded(arrays: Dict[str, jnp.ndarray],
                           score_params: Dict[str, jnp.ndarray],
                           mesh: Mesh,
                           max_rounds: int = 64,
                           max_gang_iters: int = 12,
                           herd_mode: str = "pack",
                           score_families: Tuple[str, ...] = ("binpack",),
                           use_queue_cap: bool = False,
                           use_drf_order: bool = False,
                           use_hdrf_order: bool = False,
                           fused: str = "auto") -> SolveResult:
    a = arrays
    T = a["task_init_req"].shape[0]
    N = a["node_idle"].shape[0]
    J = a["job_min"].shape[0]
    D = mesh.devices.size
    assert N % D == 0, f"node axis {N} must divide device count {D}"
    thr = a["thresholds"]
    scalar_mask = a["scalar_dim_mask"]
    counts_ready = a["task_counts_ready"].astype(jnp.int32)
    rank = a["task_rank"]
    # fused pallas choice kernel PER SHARD (ops/pallas_kernels.py): each
    # device's [T, N/D] feasibility/score/argmax pass runs in one VMEM
    # kernel; only the [T]/[N/D] reductions cross the ICI. Same gate as
    # the single-device solver, applied to the SHARD's node width.
    from ..ops.pallas_kernels import fused_choice_auto
    use_fused = fused == "on" or (
        fused == "auto" and jax.default_backend() == "tpu"
        and fused_choice_auto(T, N // D)
        and herd_mode in ("pack", "spread"))

    in_specs = {
        "task_init_req": P(), "task_req": P(), "task_job": P(),
        "task_rank": P(), "task_sig": P(), "task_counts_ready": P(),
        "task_valid": P(), "job_min": P(), "job_ready_base": P(),
        "job_queue": P(), "job_valid": P(),
        "node_idle": P("n", None), "node_extra_future": P("n", None),
        "node_used": P("n", None), "node_alloc": P("n", None),
        "node_npods": P("n"), "node_max_pods": P("n"), "node_valid": P("n"),
        "sig_masks": P(None, "n"), "thresholds": P(), "scalar_dim_mask": P(),
    }
    if use_queue_cap:
        # queue state is tiny and fairness is a global property: replicate
        # it and keep every device's bookkeeping identical (the only
        # cross-device input is the cluster-total capacity, one psum)
        in_specs.update({"queue_weight": P(), "queue_capability": P(),
                         "queue_allocated": P(), "queue_request": P()})
    if use_drf_order:
        # live DRF ordering: shares are [J] reductions over replicated
        # job state, identical on every device
        in_specs.update({"job_drf_allocated": P(), "drf_total": P(),
                         "job_drf_prerank": P()})
    if use_hdrf_order:
        # hierarchical DRF: the queue-path tree is tiny and its share
        # recursion runs on replicated [H]/[J] state (ops/hdrf.py).
        # Meaningless without the DRF ordering machinery it replaces.
        assert use_drf_order, "use_hdrf_order requires use_drf_order"
        in_specs.update({
            "hdrf_parent": P(), "hdrf_weight": P(), "hdrf_depth": P(),
            "hdrf_is_leaf": P(), "hdrf_leaf_req": P(),
            "hdrf_job_leaf": P(), "hdrf_ancestors": P(),
            "hdrf_total_allocated": P()})
    params_spec = {k: (P("n") if k == "node_static" else P())
                   for k in score_params}

    # D == 1 is a static property of the mesh: every collective below
    # degrades to identity, so they are skipped at TRACE time — the
    # compiled 1-device program contains no all_gather/psum/pmax at all
    # and the shard_map wrapper costs nothing beyond the call itself
    # (tests/test_parallel.py asserts the jaxpr is collective-free)
    D1 = D == 1

    def kernel(a, sp):
        n_loc = a["node_idle"].shape[0]
        my_base = jnp.int32(0) if D1 \
            else jax.lax.axis_index("n") * n_loc
        sig_feas = a["sig_masks"][a["task_sig"]] & a["node_valid"][None, :]
        if use_fused:
            from ..ops.pallas_kernels import fused_choice, fused_setup
            sig_i8, inv_alloc, fused_pars, node_static = fused_setup(
                {"sig_feas": sig_feas, "node_alloc": a["node_alloc"]},
                sp, a["task_init_req"].shape[1])

        if use_queue_cap:
            total_loc = jnp.sum(
                a["node_alloc"]
                * a["node_valid"][:, None].astype(jnp.float32), axis=0)
            total = total_loc if D1 else jax.lax.psum(total_loc, "n")
            Q, deserved, task_queue, q_perm, q_seg_start = queue_cap_state(
                a, rank, thr, total)
            qalloc0 = a["queue_allocated"]
            # static-sort gathers hoisted out of the round loop (see
            # ops/solver.py — the live-DRF path re-sorts per round)
            qs_q = task_queue[q_perm]
            qs_req = a["task_req"][q_perm]
        else:
            qalloc0 = jnp.zeros((1, a["node_idle"].shape[1]), jnp.float32)

        if use_drf_order:
            jobres0, drf_rank, drf_cap = drf_state(a, rank)
            if use_hdrf_order:
                # replicated [H]/[J]/[T] math: every device runs the
                # identical tree recursion + cap (ops/hdrf.py hdrf_state)
                from ..ops.hdrf import hdrf_state
                hdrf_rank_cap = hdrf_state(a, rank)
        else:
            jobres0 = jnp.zeros((1, a["node_idle"].shape[1]), jnp.float32)

        def feas_at(eligible, avail, npods, t_loc, mine):
            """Feasibility of (task, local node t_loc[task]) for this
            shard — the pointwise re-derivation the fused path uses in
            place of materializing the [T, N_loc] matrix."""
            av = avail[jnp.clip(t_loc, 0, n_loc - 1)]
            fit = le_fits(a["task_init_req"], av, thr, scalar_mask)
            sig = jnp.take_along_axis(
                sig_feas, jnp.clip(t_loc, 0, n_loc - 1)[:, None],
                axis=1)[:, 0]
            pods = (npods < a["node_max_pods"])[
                jnp.clip(t_loc, 0, n_loc - 1)]
            return fit & sig & pods & eligible & mine

        def choose(eligible, avail, idle, npods, feas0=None):
            """Global choice per task: local scoring + cross-device argmax,
            with the waterfall herd spread computed on gathered [N]
            vectors. feas0: optional precomputed fits & sig & pods mask
            (the hdrf prefilter already paid for it this round). In fused
            mode the local [T, N_loc] pass runs in the pallas kernel and
            target feasibility re-derives pointwise."""
            used_now = a["node_used"] + (a["node_idle"] - idle)
            if use_fused:
                pods_ok_v = npods < a["node_max_pods"]
                loc_val, loc_idx_l, node_score_loc = fused_choice(
                    a["task_init_req"], avail, used_now, inv_alloc,
                    node_static, eligible.astype(jnp.float32),
                    pods_ok_v.astype(jnp.float32), sig_i8, fused_pars,
                    score_families)
                loc_idx = loc_idx_l + my_base
                feas = None  # fused: no [T,N_loc] matrix materialized
            else:
                if feas0 is None:
                    pods_ok = (npods < a["node_max_pods"])[None, :]
                    feas0 = (fits_matrix(a["task_init_req"], avail, thr,
                                         scalar_mask)
                             & sig_feas & pods_ok)
                feas = feas0 & eligible[:, None]
                score = score_matrix(a["task_init_req"], avail, used_now,
                                     a["node_alloc"], sp, score_families)
                masked = jnp.where(feas, score, NEG)
                loc_val = jnp.max(masked, axis=1)                 # [T]
                loc_idx = jnp.argmax(masked, axis=1).astype(jnp.int32) \
                    + my_base
                node_score_loc = jnp.max(masked, axis=0)          # [N_loc]

            # personal best across devices (D=1: the local best IS global)
            if D1:
                has_any = loc_val > NEG / 2
                personal = jnp.where(has_any, loc_idx, -1)
            else:
                vals = jax.lax.all_gather(loc_val, "n")           # [D,T]
                idxs = jax.lax.all_gather(loc_idx, "n")           # [D,T]
                best_dev = jnp.argmax(vals, axis=0)               # [T]
                personal = jnp.take_along_axis(
                    idxs, best_dev[None, :], axis=0)[0]           # [T]
                has_any = jnp.max(vals, axis=0) > NEG / 2
                personal = jnp.where(has_any, personal, -1)

            if herd_mode in ("pack", "spread"):
                n_elig = jnp.maximum(jnp.sum(eligible), 1)
                mean_req = jnp.sum(a["task_init_req"] * eligible[:, None],
                                   axis=0) / n_elig
                sig = mean_req > jnp.where(scalar_mask, 10.0, 0.0)
                slots_dim = jnp.where(
                    sig[None, :],
                    jnp.floor((avail + thr[None, :])
                              / jnp.maximum(mean_req[None, :], 1e-9)),
                    jnp.inf)
                slots_loc = jnp.min(slots_dim, axis=1)
                slots_loc = jnp.minimum(
                    slots_loc, (a["node_max_pods"] - npods).astype(jnp.float32))
                slots_loc = jnp.clip(slots_loc, 0.0, float(T))

                if D1:
                    node_score, slots = node_score_loc, slots_loc
                else:
                    node_score = jax.lax.all_gather(
                        node_score_loc, "n", tiled=True)          # [N]
                    slots = jax.lax.all_gather(slots_loc, "n",
                                               tiled=True)
                has_slot = slots > 0
                order = jnp.argsort(-jnp.where(has_slot, node_score, NEG))
                pos = jnp.cumsum(eligible.astype(jnp.int32)) - 1
                if herd_mode == "spread":
                    # near-best striping (ops/solver.py _waterfall_choice):
                    # stripe only across nodes tying the best herd score
                    masked_ns = jnp.where(has_slot, node_score, NEG)
                    best_s = jnp.max(masked_ns)
                    eps = 1e-5 * jnp.maximum(jnp.abs(best_s), 1.0)
                    near = has_slot & (masked_ns >= best_s - eps)
                    m = jnp.maximum(jnp.sum(near), 1)
                    target = order[jnp.mod(jnp.maximum(pos, 0), m)]
                else:
                    cum = jnp.cumsum(slots[order])
                    idx = jnp.searchsorted(cum, pos.astype(jnp.float32),
                                           side="right")
                    target = order[jnp.clip(idx, 0, N - 1)]
                target = target.astype(jnp.int32)
                # feasibility of each task at its (possibly remote) target
                t_loc = target - my_base
                mine = (t_loc >= 0) & (t_loc < n_loc)
                if feas is None:  # fused path: pointwise re-derivation
                    t_ok_loc = feas_at(eligible, avail, npods, t_loc, mine)
                else:
                    t_ok_loc = jnp.take_along_axis(
                        feas, jnp.clip(t_loc, 0, n_loc - 1)[:, None],
                        axis=1)[:, 0] & mine
                t_ok = t_ok_loc if D1 else (
                    jax.lax.psum(t_ok_loc.astype(jnp.int32), "n") > 0)
                choice = jnp.where(t_ok, target, personal)
            else:
                choice = personal
            return choice

        def admit_local(choice, avail, npods, r_rank):
            """Admission for choices landing in this device's shard
            (feasibility of the chosen node was already established by
            choose(); the prefix re-checks capacity only)."""
            c_loc = choice - my_base
            mine = (c_loc >= 0) & (c_loc < n_loc) & (choice >= 0)
            c_loc = jnp.where(mine, c_loc, -1)
            key = jnp.where(mine, c_loc * (T + 1) + r_rank, BIG_KEY)
            perm = jnp.argsort(key)
            s_choice = c_loc[perm]
            s_active = s_choice >= 0
            s_fit = a["task_init_req"][perm] * s_active[:, None]
            seg_start = jnp.concatenate(
                [jnp.array([True]), s_choice[1:] != s_choice[:-1]])
            prefix = _segment_prefix(s_fit, seg_start)
            s_avail = avail[jnp.maximum(s_choice, 0)]
            fits = le_fits(prefix + s_fit, s_avail, thr, scalar_mask,
                           ignore_req=s_fit) & s_active
            ones = jnp.ones_like(s_choice)
            pos = _segment_prefix(
                ones[:, None].astype(jnp.float32), seg_start)[:, 0]
            pods_fit = (npods[jnp.maximum(s_choice, 0)] + pos) \
                < a["node_max_pods"][jnp.maximum(s_choice, 0)]
            admit_sorted = fits & pods_fit
            admit = jnp.zeros(T, dtype=bool).at[perm].set(admit_sorted)
            debit = jax.ops.segment_sum(
                a["task_req"] * admit[:, None], jnp.maximum(c_loc, 0),
                num_segments=n_loc)
            pod_inc = jax.ops.segment_sum(
                admit.astype(jnp.int32), jnp.maximum(c_loc, 0),
                num_segments=n_loc)
            # global admitted assignment: each task admitted on one device
            new_assign = jnp.where(admit, choice, -1)
            if not D1:
                new_assign = jax.lax.pmax(new_assign, "n")        # [T]
            return new_assign, debit, pod_inc

        def phase_rounds(st, use_future, capped=True):
            def cond(s):
                return s[-1] & (s[-2] < max_rounds)

            def body(s):
                (idle, pipe, npods, qalloc, jobres, assigned, kind,
                 excluded, rounds, _) = s
                avail = (idle + a["node_extra_future"] - pipe) if use_future \
                    else idle
                eligible = (a["task_valid"] & (assigned < 0)
                            & ~excluded[a["task_job"]])
                feas0 = None
                if use_drf_order:
                    if use_hdrf_order:
                        # placeability prefilter (see ops/solver.py): a
                        # task no node in ANY shard can take must not
                        # hold its sibling group's min key or budget.
                        # Dense mode hands feas0 to choose() so the
                        # [T,N_loc] matrix is built once per round; fused
                        # mode pays one extra kernel pass instead.
                        pods_ok_v = npods < a["node_max_pods"]
                        if use_fused:
                            used_now0 = a["node_used"] \
                                + (a["node_idle"] - idle)
                            best_s0, _, _ = fused_choice(
                                a["task_init_req"], avail, used_now0,
                                inv_alloc, node_static,
                                eligible.astype(jnp.float32),
                                pods_ok_v.astype(jnp.float32), sig_i8,
                                fused_pars, score_families)
                            if not D1:
                                best_s0 = jax.lax.pmax(best_s0, "n")
                            placeable = best_s0 > NEG * 0.5
                        else:
                            feas0 = (fits_matrix(a["task_init_req"],
                                                 avail, thr, scalar_mask)
                                     & sig_feas & pods_ok_v[None, :])
                            any_loc = jnp.any(feas0, axis=1)
                            placeable = any_loc if D1 else (
                                jax.lax.psum(any_loc.astype(jnp.int32),
                                             "n") > 0)
                        r_rank, eligible = hdrf_rank_cap(
                            eligible & placeable, jobres)
                    else:
                        r_rank = drf_rank(jobres)
                        eligible = drf_cap(eligible, jobres)
                else:
                    r_rank = rank
                if use_queue_cap:
                    # overflow pass relaxes deserved, never capability
                    bound = deserved if capped else a["queue_capability"]
                    qrem = jnp.maximum(bound - qalloc, 0.0)
                    if use_drf_order:
                        qp = jnp.lexsort((r_rank, task_queue))
                        eligible = eligible & _queue_cap_mask(
                            eligible, task_queue, a["task_req"], qrem,
                            thr, scalar_mask, qp, q_seg_start)
                    else:
                        eligible = eligible & _queue_cap_mask(
                            eligible, task_queue, a["task_req"], qrem,
                            thr, scalar_mask, q_perm, q_seg_start,
                            qs_q, qs_req)
                choice = choose(eligible, avail, idle, npods, feas0)
                new_assign, debit, pod_inc = admit_local(
                    choice, avail, npods, r_rank)
                got = new_assign >= 0
                assigned = jnp.where(got, new_assign, assigned)
                kind = jnp.where(got, jnp.int32(1 if use_future else 0), kind)
                if use_queue_cap:
                    # got is replicated (pmax in admit_local), so every
                    # device books identical queue allocations
                    qalloc = qalloc + jax.ops.segment_sum(
                        a["task_req"] * got[:, None], task_queue,
                        num_segments=Q)
                if use_drf_order:
                    jobres = jobres + jax.ops.segment_sum(
                        a["task_req"] * got[:, None], a["task_job"],
                        num_segments=J)
                if use_future:
                    pipe = pipe + debit
                else:
                    idle = idle - debit
                    npods = npods + pod_inc
                return (idle, pipe, npods, qalloc, jobres, assigned, kind,
                        excluded, rounds + 1, jnp.any(got))

            out = jax.lax.while_loop(cond, body, st + (jnp.bool_(True),))
            return out[:-1]

        # job order position for the gang-exclusion tie-break (replicated)
        job_first_rank = jnp.full((J,), T, jnp.int32).at[a["task_job"]].min(
            jnp.where(a["task_valid"], rank, T))

        def gang_body(s):
            (idle, pipe, npods, qalloc, jobres, assigned, kind, excluded,
             rounds, _, it, revert_count, deferred, processed) = s
            # deferred-retry queue, replicated math (see ops/solver.py
            # gang_body): doubly-reverted jobs retry one at a time in rank
            # order while the rest sit out
            unproc = deferred & ~processed & ~excluded
            cur = jnp.argmin(jnp.where(unproc, job_first_rank, BIG_KEY))
            solo = unproc & (jnp.arange(J) == cur)
            barred = deferred & ~solo
            st = (idle, pipe, npods, qalloc, jobres, assigned, kind,
                  excluded | barred, rounds)
            st = phase_rounds(st, False)
            st = phase_rounds(st, True)
            if use_queue_cap:
                # work-conserving overflow (see ops/solver.py phase_rounds)
                st = phase_rounds(st, False, capped=False)
                st = phase_rounds(st, True, capped=False)
            (idle, pipe, npods, qalloc, jobres, assigned, kind, _masked,
             rounds) = st
            alloc_counts = jax.ops.segment_sum(
                ((assigned >= 0) & (kind == 0)).astype(jnp.int32)
                * counts_ready, a["task_job"], num_segments=J)
            ready = ((a["job_ready_base"] + alloc_counts) >= a["job_min"]) \
                & a["job_valid"]
            has_alloc = jax.ops.segment_sum(
                ((assigned >= 0) & (kind == 0)).astype(jnp.int32),
                a["task_job"], num_segments=J) > 0
            revert_job = ~ready & a["job_valid"] & ~excluded & ~barred \
                & has_alloc
            revert_task = (revert_job[a["task_job"]] & (assigned >= 0)
                           & (kind == 0))
            # credit back to this shard's nodes only
            rv_loc = jnp.where(revert_task, assigned - my_base, -1)
            rv_mine = (rv_loc >= 0) & (rv_loc < n_loc)
            credit = jax.ops.segment_sum(
                a["task_req"] * rv_mine[:, None], jnp.maximum(rv_loc, 0),
                num_segments=n_loc)
            pod_credit = jax.ops.segment_sum(
                rv_mine.astype(jnp.int32), jnp.maximum(rv_loc, 0),
                num_segments=n_loc)
            idle = idle + credit
            npods = npods - pod_credit
            if use_queue_cap:
                qalloc = qalloc - jax.ops.segment_sum(
                    a["task_req"] * revert_task[:, None], task_queue,
                    num_segments=Q)
            if use_drf_order:
                jobres = jobres - jax.ops.segment_sum(
                    a["task_req"] * revert_task[:, None], a["task_job"],
                    num_segments=J)
            assigned = jnp.where(revert_task, -1, assigned)
            kind = jnp.where(revert_task, -1, kind)
            # retry policy matches the single-device gang fixpoint
            # (ops/solver.py gang_body): first revert retries in parallel,
            # second defers to the solo queue, a failed solo excludes
            revert_count = revert_count + revert_job.astype(jnp.int32)
            excluded = excluded | (solo & revert_job)
            processed = processed | (solo & jnp.any(unproc))
            deferred = deferred | (revert_job & (revert_count >= 2))
            any_more = jnp.any(revert_job) | jnp.any(
                deferred & ~processed & ~excluded)
            return (idle, pipe, npods, qalloc, jobres, assigned, kind,
                    excluded, rounds, any_more, it + 1,
                    revert_count, deferred, processed)

        init = (a["node_idle"], jnp.zeros_like(a["node_idle"]),
                a["node_npods"], qalloc0, jobres0,
                jnp.full((T,), -1, jnp.int32),
                jnp.full((T,), -1, jnp.int32), ~a["job_valid"],
                jnp.int32(0), jnp.bool_(True), jnp.int32(0),
                jnp.zeros(J, jnp.int32), jnp.zeros(J, dtype=bool),
                jnp.zeros(J, dtype=bool))
        s = jax.lax.while_loop(
            lambda s: s[-5] & (s[-4] < max_gang_iters), gang_body, init)
        (idle, pipe, npods, _, _, assigned, kind, excluded, rounds,
         _, _, _, _, _) = s
        alloc_counts = jax.ops.segment_sum(
            ((assigned >= 0) & (kind == 0)).astype(jnp.int32) * counts_ready,
            a["task_job"], num_segments=J)
        job_ready = ((a["job_ready_base"] + alloc_counts) >= a["job_min"]) \
            & a["job_valid"]
        return assigned, kind, job_ready, rounds

    mapped = shard_map(
        kernel, mesh=mesh,
        in_specs=(in_specs, params_spec),
        out_specs=(P(), P(), P(), P()))
    # device_dict may carry extra arrays (queue fairness) this kernel
    # doesn't consume; keep the pytree congruent with in_specs
    assigned, kind, job_ready, rounds = mapped(
        {k: a[k] for k in in_specs}, dict(score_params))
    return SolveResult(assigned=assigned, kind=kind, job_ready=job_ready,
                       rounds=rounds)


@functools.partial(jax.jit, static_argnames=(
    "layout", "mesh", "max_rounds", "max_gang_iters", "herd_mode",
    "score_families", "use_queue_cap", "use_drf_order", "use_hdrf_order",
    "fused"))
def solve_allocate_sharded_packed2d(f2d, i2d, layout,
                                    score_params, mesh: Mesh,
                                    max_rounds: int = 64,
                                    max_gang_iters: int = 12,
                                    herd_mode: str = "pack",
                                    score_families=("binpack",),
                                    use_queue_cap: bool = False,
                                    use_drf_order: bool = False,
                                    use_hdrf_order: bool = False,
                                    fused: str = "auto") -> SolveResult:
    """Sharded solve over the chunked device-resident buffers kept by
    ops.device_cache.PackedDeviceCache: the unpack slices fuse away on
    device, so a sharded deployment ships only dirty chunks per session
    exactly like the single-device path — no host re-upload and, at D=1,
    no re-sharding of the resident buffers on entry."""
    from ..ops.solver import _unpack

    nf = max(off + size for k, kind, off, size, shape in layout
             if kind == "f")
    ni = max(off + size for k, kind, off, size, shape in layout
             if kind != "f")
    arrays = _unpack(f2d.reshape(-1)[:nf], i2d.reshape(-1)[:ni], layout)
    return solve_allocate_sharded(arrays, score_params, mesh, max_rounds,
                                  max_gang_iters, herd_mode,
                                  score_families, use_queue_cap,
                                  use_drf_order, use_hdrf_order, fused)


@functools.partial(jax.jit, static_argnames=(
    "rep_layout", "node_layout", "mesh", "max_rounds", "max_gang_iters",
    "herd_mode", "score_families", "use_queue_cap", "use_drf_order",
    "use_hdrf_order", "fused"))
def solve_allocate_sharded_arena(f_rep, i_rep, f_node, i_node,
                                 rep_layout, node_layout,
                                 score_params, mesh: Mesh,
                                 max_rounds: int = 64,
                                 max_gang_iters: int = 12,
                                 herd_mode: str = "pack",
                                 score_families=("binpack",),
                                 use_queue_cap: bool = False,
                                 use_drf_order: bool = False,
                                 use_hdrf_order: bool = False,
                                 fused: str = "auto") -> SolveResult:
    """Sharded solve over the SHARDED device-resident arena
    (ops.device_cache.ShardedDeviceCache): ``f_rep``/``i_rep`` are the
    replicated chunked task/job buffers, ``f_node``/``i_node`` the
    ``[D, C, chunk]`` node buffers sharded along the mesh 'n' axis (one
    resident slab per device). The unpack below is sharding-preserving —
    slicing the chunked slabs and merging the leading shard axis keeps
    every node array split exactly as the shard_map in_specs demand, so a
    steady sharded session dispatches straight off the resident shards
    with no host re-upload and no cross-device resharding."""
    from ..ops.device_cache import NODE_COL_KEYS
    from ..ops.solver import _unpack

    D = mesh.devices.size
    nf = max((off + size for _k, kind, off, size, _s in rep_layout
              if kind == "f"), default=0)
    ni = max((off + size for _k, kind, off, size, _s in rep_layout
              if kind != "f"), default=0)
    arrays = _unpack(f_rep.reshape(-1)[:max(nf, 1)],
                     i_rep.reshape(-1)[:max(ni, 1)],
                     tuple(e for e in rep_layout))
    fn = f_node.reshape(D, -1)
    im = i_node.reshape(D, -1)
    for key, kind, off, size, pshape in node_layout:
        src = fn if kind == "f" else im
        v = src[:, off:off + size].reshape((D,) + tuple(pshape))
        if kind == "b":
            v = v.astype(bool)
        if key in NODE_COL_KEYS:
            # [D, S, N/D] -> [S, N]: the merged axis stays sharded on 'n'
            v = v.transpose(1, 0, 2).reshape(pshape[0], D * pshape[1])
        else:
            v = v.reshape((D * pshape[0],) + tuple(pshape[1:]))
        arrays[key] = v
    return solve_allocate_sharded(arrays, score_params, mesh, max_rounds,
                                  max_gang_iters, herd_mode,
                                  score_families, use_queue_cap,
                                  use_drf_order, use_hdrf_order, fused)
