"""Device-mesh sharding of the solver (the multi-chip scale axis) and the
solver-sidecar process boundary."""

from .sharded_solver import make_mesh, solve_allocate_sharded  # noqa: F401
from .sidecar import SidecarSolver, SolverServer  # noqa: F401
