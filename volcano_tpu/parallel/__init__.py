"""Device-mesh sharding of the solver (the multi-chip scale axis) and the
solver-sidecar process boundary."""

from .sharded_evict import solve_evict_uniform_sharded  # noqa: F401
from .sharded_solver import (  # noqa: F401
    arena_mesh, make_mesh, solve_allocate_sharded,
    solve_allocate_sharded_arena, solve_allocate_sharded_packed2d,
)
from .sidecar import SidecarSolver, SolverServer  # noqa: F401
