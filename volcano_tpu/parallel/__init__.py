"""Device-mesh sharding of the solver (the multi-chip scale axis)."""

from .sharded_solver import make_mesh, solve_allocate_sharded  # noqa: F401
