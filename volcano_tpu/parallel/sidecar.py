"""Solver sidecar: the snapshot-request / assignment-response process
boundary (BASELINE.json north-star architecture).

The reference's scheduler is separated from its cluster by the Kubernetes
API-server protocol (informers in, bind/evict writes out —
pkg/scheduler/cache/cache.go:319-402). The TPU build's analogous seam
splits the control plane (session, statement, plugins, effectors) from the
JAX solver: the control plane packs the snapshot (SnapshotArrays.packed)
and ships it over a local unix socket; the sidecar process owns the TPU,
keeps the buffers device-resident across sessions (PackedDeviceCache —
deltas computed server-side, so the socket carries plain full buffers),
runs the solve, and returns the compact assignment vector.

Why a process boundary: the control plane stays a lightweight pure-Python
process (restartable, debuggable, no TPU runtime linked in — the drop-in
property the reference gets from speaking only the API-server protocol),
while the solver process pins the chip. Protocol: length-prefixed frames,
a JSON header + raw little-endian array bytes; no serialization library
needed and nothing to keep in sync with a schema compiler.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

_MAGIC = b"VTS1"

#: frame-size ceilings (ADVICE r2 #4): a corrupt or hostile local client
#: must not drive unbounded allocation. 1 MiB of JSON header and 256 MiB
#: per blob dwarf any real snapshot (10k x 2k packs to ~0.5 MB) while
#: keeping a garbage length prefix from OOMing the solver process.
MAX_HEADER_BYTES = 1 << 20
MAX_BLOB_BYTES = 256 << 20


# -- framing ----------------------------------------------------------------

def _send_frame(sock: socket.socket, header: dict, blobs) -> None:
    meta = dict(header)
    meta["blobs"] = [{"dtype": str(b.dtype), "shape": list(b.shape)}
                     for b in blobs]
    hdr = json.dumps(meta).encode()
    sock.sendall(_MAGIC + struct.pack("<I", len(hdr)) + hdr)
    for b in blobs:
        raw = np.ascontiguousarray(b).tobytes()
        sock.sendall(struct.pack("<Q", len(raw)) + raw)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("sidecar socket closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket):
    magic = _recv_exact(sock, 4)
    if magic != _MAGIC:
        raise ConnectionError(f"bad magic {magic!r}")
    (hlen,) = struct.unpack("<I", _recv_exact(sock, 4))
    if hlen > MAX_HEADER_BYTES:
        raise ConnectionError(f"header length {hlen} exceeds cap "
                              f"{MAX_HEADER_BYTES}")
    header = json.loads(_recv_exact(sock, hlen))
    blobs = []
    for spec in header.pop("blobs", []):
        (blen,) = struct.unpack("<Q", _recv_exact(sock, 8))
        if blen > MAX_BLOB_BYTES:
            raise ConnectionError(f"blob length {blen} exceeds cap "
                                  f"{MAX_BLOB_BYTES}")
        arr = np.frombuffer(_recv_exact(sock, blen),
                            dtype=np.dtype(spec["dtype"]))
        blobs.append(arr.reshape(spec["shape"]))
    return header, blobs


def _layout_wire(layout):
    return [[k, kind, off, size, list(shape)]
            for k, kind, off, size, shape in layout]


def _layout_unwire(wire):
    return tuple((k, kind, off, size, tuple(shape))
                 for k, kind, off, size, shape in wire)


# -- server (owns the TPU) --------------------------------------------------

class SolverServer:
    """Accept loop serving solve requests; one at a time (one chip)."""

    def __init__(self, path: str):
        self.path = path
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._device_cache = None

    def _ensure(self):
        if self._device_cache is None:
            from ..ops.device_cache import PackedDeviceCache
            self._device_cache = PackedDeviceCache()
        return self._device_cache

    def _solve(self, header, blobs):
        from ..ops.solver import solve_allocate_packed2d

        fbuf, ibuf = blobs[0], blobs[1]
        params = {}
        for name, blob in zip(header["param_names"], blobs[2:]):
            params[name] = blob if blob.ndim else np.float32(blob)
        layout = _layout_unwire(header["layout"])
        dcache = self._ensure()
        f2d, i2d = dcache.update(fbuf, ibuf, layout)
        res = solve_allocate_packed2d(
            f2d, i2d, layout, params,
            herd_mode=header["herd_mode"],
            score_families=tuple(header["score_families"]),
            use_queue_cap=header["use_queue_cap"],
            use_drf_order=header.get("use_drf_order", False),
            use_hdrf_order=header.get("use_hdrf_order", False),
            work_conserving=header.get("work_conserving", True))
        return {"rounds": int(np.asarray(res.rounds)),
                "shipped_chunks": dcache.last_shipped_chunks}, \
            [np.asarray(res.assigned), np.asarray(res.kind)]

    def _solve_evict(self, header, blobs):
        """Eviction solve: arrays/victims/params arrive as named blobs;
        the uniform fast path is chosen when the victim dict carries
        job_req/job_acct/job_count (the client's uniformity verdict)."""
        from ..ops.evict import solve_evict, solve_evict_uniform

        names = header["blob_names"]
        arrays, victims, params = {}, {}, {}
        for name, blob in zip(names, blobs):
            group, key = name.split(".", 1)
            val = blob if blob.ndim else np.float32(blob)
            {"a": arrays, "v": victims, "p": params}[group][key] = val
        families = tuple(header["score_families"])
        if "job_req" in victims:
            res = solve_evict_uniform(
                arrays, victims, params, score_families=families,
                require_freed_covers=header["require_freed_covers"],
                stop_at_need=header["stop_at_need"])
        else:
            res = solve_evict(
                arrays, victims, params, score_families=families,
                require_freed_covers=header["require_freed_covers"],
                allow_revert=header["allow_revert"],
                stop_at_need=header["stop_at_need"])
        return {}, [np.asarray(res.assigned), np.asarray(res.evicted_by)]

    def serve_forever(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.path)
        # backlog > 1 so a second client connects instead of hanging in
        # the kernel queue forever; it gets an explicit busy error below
        self._listener.listen(4)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                break
            with conn:
                try:
                    while True:
                        # between frames, watch the listener too: a second
                        # client gets an explicit busy error instead of
                        # queueing silently behind this one (one chip, one
                        # client at a time). The served connection is
                        # handled FIRST: when it has pending data or EOF
                        # (e.g. a restarting scheduler whose old socket
                        # just closed), that must resolve before any
                        # busy-reject, or the legitimate reconnect would
                        # be bounced while the stale client is already
                        # gone.
                        import select as _select
                        ready, _, _ = _select.select(
                            [conn, self._listener], [], [])
                        if conn not in ready:
                            # only the listener is ready: the served
                            # client is verifiably alive-and-idle (a dead
                            # one would be readable with EOF)
                            try:
                                conn2, _ = self._listener.accept()
                                with conn2:
                                    _send_frame(conn2, {
                                        "error": "busy: another client "
                                                 "is being served"}, [])
                            except OSError:
                                pass
                            continue
                        header, blobs = _recv_frame(conn)
                        if header.get("op") == "shutdown":
                            self._stop.set()
                            return
                        try:
                            if header.get("op") == "solve_evict":
                                out_header, out_blobs = self._solve_evict(
                                    header, blobs)
                            else:
                                out_header, out_blobs = self._solve(header,
                                                                    blobs)
                        except Exception as e:  # noqa: BLE001
                            # a bad request must not kill the server or
                            # leave the client hanging: answer with an
                            # error frame and keep serving
                            out_header = {"error": f"{type(e).__name__}: "
                                                   f"{e}"}
                            out_blobs = []
                        _send_frame(conn, out_header, out_blobs)
                except (ConnectionError, OSError):
                    continue  # client went away; await the next one

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.close()


# -- client (the control plane side) ----------------------------------------

class SidecarSolver:
    """Drop-in allocate solve over the sidecar socket. The allocate action
    uses it instead of the in-process kernel when the session exposes one
    (SchedulerCache.sidecar)."""

    def __init__(self, path: str, timeout: float = 120.0):
        self.path = path
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self.timeout)
            s.connect(self.path)
            self._sock = s
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def shutdown_server(self) -> None:
        sock = self._connect()
        _send_frame(sock, {"op": "shutdown"}, [])
        self.close()

    def _request(self, header, blobs):
        try:
            sock = self._connect()
            _send_frame(sock, header, blobs)
            out_header, out_blobs = _recv_frame(sock)
        except (ConnectionError, OSError):
            self.close()
            raise
        if "error" in out_header:
            raise RuntimeError(
                f"sidecar {header.get('op')} failed: {out_header['error']}")
        return out_header, out_blobs

    def solve(self, fbuf, ibuf, layout, params,
              herd_mode: str = "pack",
              score_families: Tuple[str, ...] = ("binpack",),
              use_queue_cap: bool = False,
              use_drf_order: bool = False,
              use_hdrf_order: bool = False,
              work_conserving: bool = True):
        """Returns (assigned [T] int32, kind [T] int32, info dict)."""
        names, blobs = [], [fbuf, ibuf]
        for name, val in params.items():
            names.append(name)
            blobs.append(np.asarray(val))
        header = {
            "op": "solve",
            "layout": _layout_wire(layout),
            "param_names": names,
            "herd_mode": herd_mode,
            "score_families": list(score_families),
            "use_queue_cap": bool(use_queue_cap),
            "use_drf_order": bool(use_drf_order),
            "use_hdrf_order": bool(use_hdrf_order),
            "work_conserving": bool(work_conserving),
        }
        out_header, out_blobs = self._request(header, blobs)
        return out_blobs[0], out_blobs[1], out_header

    def solve_evict(self, arrays, victims, params,
                    score_families: Tuple[str, ...] = ("kube",),
                    require_freed_covers: bool = False,
                    allow_revert: bool = True,
                    stop_at_need: bool = True):
        """Eviction solve over the socket (preempt/reclaim). Returns
        (assigned [T] int32, evicted_by [V] int32).

        Arrays ship as raw named blobs, unlike allocate's delta-cached
        packed buffers: the sidecar sits next to its chip (unix socket +
        local PCIe/ICI), evict runs only when preempt/reclaim are
        configured, and its flatten has a different task set per call —
        a second delta cache would mostly thrash."""
        names, blobs = [], []
        for group, d in (("a", arrays), ("v", victims), ("p", params)):
            for key, val in d.items():
                names.append(f"{group}.{key}")
                blobs.append(np.asarray(val))
        header = {
            "op": "solve_evict",
            "blob_names": names,
            "score_families": list(score_families),
            "require_freed_covers": bool(require_freed_covers),
            "allow_revert": bool(allow_revert),
            "stop_at_need": bool(stop_at_need),
        }
        _, out_blobs = self._request(header, blobs)
        return out_blobs[0], out_blobs[1]


def main(argv=None) -> int:
    """``python -m volcano_tpu.parallel.sidecar /path/to.sock`` — the
    solver process entry point (owns the TPU)."""
    import argparse

    ap = argparse.ArgumentParser(prog="volcano-solver-sidecar")
    ap.add_argument("socket_path")
    args = ap.parse_args(argv)
    server = SolverServer(args.socket_path)
    print(f"solver sidecar listening on {args.socket_path}", flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
